"""AutoEstimator — hyperparameter search over model creators.

Rebuild of ``pyzoo/zoo/orca/automl/auto_estimator.py:19``
(``AutoEstimator.from_torch/from_keras`` + ``fit(data, search_space,
n_sampling, metric)``). A creator receives a sampled ``config`` dict and
returns a ready-to-train model; each trial trains on the mesh and reports
the validation metric; the best trial's model is retained.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from zoo_tpu.automl.search import make_search_engine


_MINIMIZE = {"mse", "rmse", "mae", "smape", "loss", "binary_crossentropy"}


class AutoEstimator:
    def __init__(self, model_builder: Callable[[Dict], Any],
                 kind: str = "keras"):
        self.model_builder = model_builder
        self.kind = kind
        self._best_model = None
        self._best_config: Optional[Dict] = None
        self._best_metric: Optional[float] = None

    # -- factories (reference API) ----------------------------------------
    @staticmethod
    def from_keras(*, model_creator: Callable[[Dict], Any],
                   **kwargs) -> "AutoEstimator":
        """``model_creator(config)`` returns a COMPILED zoo_tpu keras model
        (reference: ``from_keras`` builds a KerasModelBuilder)."""
        return AutoEstimator(model_creator, kind="keras")

    @staticmethod
    def from_torch(*, model_creator: Callable[[Dict], Any],
                   optimizer=None, loss=None, **kwargs) -> "AutoEstimator":
        """``model_creator(config)`` returns a torch nn.Module; optimizer
        and loss as in the PyTorch Estimator (reference: ``from_torch``) —
        creator functions are forwarded to ``Estimator.from_torch`` so the
        optimizer creator receives the REAL model."""
        def build(config: Dict):
            from zoo_tpu.orca.learn.pytorch import Estimator as TorchEst
            from zoo_tpu.orca.learn.pytorch.estimator import _is_torch_loss

            kw: Dict[str, Any] = {}
            if callable(optimizer) and not isinstance(optimizer, str):
                kw["optimizer_creator"] = optimizer
            else:
                kw["optimizer"] = optimizer
            if callable(loss) and not _is_torch_loss(loss):
                kw["loss_creator"] = loss
            else:
                kw["loss"] = loss
            return TorchEst.from_torch(model_creator=model_creator,
                                       config=config, **kw)

        return AutoEstimator(build, kind="torch")

    # -- search ------------------------------------------------------------
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            validation_data=None, metric: str = "mse",
            metric_mode: Optional[str] = None,
            search_space: Optional[Dict] = None, n_sampling: int = 1,
            seed: int = 0, search_alg=None,
            scheduler=None, n_parallel: int = 1) -> "AutoEstimator":
        """Run the search (reference: ``AutoEstimator.fit`` with
        ``search_space``/``n_sampling``/``metric``; ``search_alg``/
        ``scheduler`` mirror ray.tune's knobs,
        ``ray_tune_search_engine.py:29,151`` — ``"tpe"`` for model-based
        sampling, ``"asha"`` for successive-halving early stopping).

        ``n_parallel``: run that many trials CONCURRENTLY, each on its
        own disjoint sub-mesh of the ambient devices (the TPU-native
        form of Ray Tune's parallel trials; needs
        ``len(devices) >= n_parallel``). TPE stays sequential — its
        suggestions condition on every completed trial."""
        if search_space is None:
            raise ValueError("search_space is required")
        mode = metric_mode or ("min" if metric.lower() in _MINIMIZE
                               else "max")
        eval_data = validation_data if validation_data is not None else data

        def _xy(d):
            return d if isinstance(d, tuple) else (d, None)

        def trial_fn(config: Dict, reporter=None) -> Dict:
            bs = int(config.pop("batch_size", batch_size))
            model = self.model_builder(config)
            if hasattr(model, "torch_model"):  # PyTorchEstimator
                if reporter is None:
                    model.fit(data, epochs=epochs, batch_size=bs)
                    res = model.evaluate(eval_data, batch_size=bs)
                else:  # per-epoch reporting for the ASHA scheduler
                    res = {}
                    for e in range(epochs):
                        model.fit(data, epochs=1, batch_size=bs)
                        res = model.evaluate(eval_data, batch_size=bs)
                        val = res.get(metric, res.get("loss"))
                        if val is None:
                            raise ValueError(
                                f"metric {metric!r} not produced by "
                                f"evaluate(); available: {sorted(res)}")
                        if reporter(e + 1, float(val)):
                            break
            else:  # compiled keras-facade model
                x, y = _xy(data)
                ex, ey = _xy(eval_data)
                if reporter is None:
                    model.fit(x, y, batch_size=bs, nb_epoch=epochs,
                              verbose=0)
                    res = model.evaluate(ex, ey, batch_size=bs)
                else:
                    res = {}
                    for e in range(epochs):
                        # seed varies per epoch: each nb_epoch=1 call
                        # re-creates the shuffle/dropout RNGs, and a
                        # constant seed would repeat the identical
                        # permutation and masks every epoch
                        model.fit(x, y, batch_size=bs, nb_epoch=1,
                                  verbose=0, seed=seed + e)
                        res = model.evaluate(ex, ey, batch_size=bs)
                        val = res.get(metric, res.get("loss"))
                        if val is None:
                            raise ValueError(
                                f"metric {metric!r} not produced by "
                                f"evaluate(); available: {sorted(res)}")
                        if reporter(e + 1, float(val)):
                            break
            if metric not in res:
                # res["loss"] may stand in for the metric only when the
                # compiled loss really is that metric. (For the torch path
                # the name lives on the inner KerasNet / the torch loss.)
                loss_name = (getattr(model, "loss_name", None)
                             or getattr(getattr(model, "model", None),
                                        "loss_name", None)
                             or type(getattr(model, "loss", None)
                                     ).__name__ or "").lower()
                torch_aliases = {"mseloss": "mse", "l1loss": "mae"}
                loss_name = torch_aliases.get(loss_name, loss_name)
                aliases = {"mse": {"mse", "mean_squared_error"},
                           "mae": {"mae", "mean_absolute_error"}}
                wanted = aliases.get(metric.lower(), {metric.lower()})
                if metric == "loss" or (set(res) == {"loss"}
                                        and loss_name in wanted):
                    value = res["loss"]
                else:
                    raise ValueError(
                        f"metric {metric!r} not produced by evaluate(); "
                        f"available: {sorted(res)} — compile the model "
                        f"with metrics=[{metric!r}]")
            else:
                value = res[metric]
            return {metric: float(value), "model": model}

        engine = make_search_engine(search_alg=search_alg,
                                    scheduler=scheduler,
                                    n_parallel=n_parallel)
        engine.compile(trial_fn, search_space, n_sampling=n_sampling,
                       metric=metric, mode=mode, seed=seed)
        engine.run()
        best = engine.get_best_trial()
        self._best_config = dict(best.config)
        self._best_metric = best.metric
        self._best_model = best.artifacts.get("model")
        return self

    def get_best_model(self):
        if self._best_model is None:
            raise RuntimeError("fit() first")
        return self._best_model

    def get_best_config(self) -> Dict:
        if self._best_config is None:
            raise RuntimeError("fit() first")
        return dict(self._best_config)

    @property
    def best_metric(self) -> float:
        return self._best_metric
