"""XGBoost + AutoXGBoost (reference: ``orca/automl/xgboost/XGBoost.py:1``,
``auto_xgb.py``).

The reference sparkles ``xgboost`` regressors/classifiers and searches
their hyperparameters through AutoEstimator. The ``xgboost`` package is
not in this image, so the wrapper trains through it when importable and
otherwise falls back to sklearn's histogram gradient boosting (the same
algorithm family with the same core knobs: n_estimators→max_iter,
max_depth, learning_rate, reg_lambda) — callers keep one API either way.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def _backend():
    try:
        import xgboost
        return "xgboost"
    except ImportError:
        return "sklearn"


class _XGBBase:
    _objective = "reg"

    def __init__(self, config: Optional[Dict] = None, **params):
        cfg = dict(config or {})
        cfg.update(params)
        self.n_estimators = int(cfg.pop("n_estimators", 100))
        self.max_depth = cfg.pop("max_depth", None)
        self.learning_rate = float(cfg.pop("learning_rate",
                                           cfg.pop("lr", 0.1)))
        self.reg_lambda = float(cfg.pop("lambda",
                                        cfg.pop("reg_lambda", 1.0)))
        self.extra = cfg
        self.backend = _backend()
        self.model = None

    def _build(self):
        if self.backend == "xgboost":
            import xgboost as xgb
            cls = (xgb.XGBRegressor if self._objective == "reg"
                   else xgb.XGBClassifier)
            return cls(n_estimators=self.n_estimators,
                       max_depth=self.max_depth,
                       learning_rate=self.learning_rate,
                       reg_lambda=self.reg_lambda, **self.extra)
        from sklearn.ensemble import (
            HistGradientBoostingClassifier,
            HistGradientBoostingRegressor,
        )
        cls = (HistGradientBoostingRegressor if self._objective == "reg"
               else HistGradientBoostingClassifier)
        return cls(max_iter=self.n_estimators, max_depth=self.max_depth,
                   learning_rate=self.learning_rate,
                   l2_regularization=self.reg_lambda)

    def fit(self, x, y, validation_data=None) -> "_XGBBase":
        if self.backend != "xgboost" and self.extra:
            import warnings
            warnings.warn(
                f"xgboost not installed; sklearn fallback ignores extra "
                f"hyperparameters {sorted(self.extra)}")
        self.model = self._build()
        self.model.fit(np.asarray(x), np.asarray(y))
        return self

    def predict(self, x) -> np.ndarray:
        if self.model is None:
            raise RuntimeError("call fit() first")
        return np.asarray(self.model.predict(np.asarray(x)))

    def evaluate(self, x, y, metrics=("mse",)) -> Dict[str, float]:
        pred = self.predict(x)
        y = np.asarray(y)
        out = {}
        for m in metrics:
            key = m.lower()
            if key == "mse":
                out[key] = float(np.mean((pred - y) ** 2))
            elif key == "mae":
                out[key] = float(np.mean(np.abs(pred - y)))
            elif key in ("accuracy", "acc"):
                out[key] = float(np.mean(pred == y))
            elif key == "logloss":
                proba = np.clip(self.model.predict_proba(
                    np.asarray(x)), 1e-7, 1 - 1e-7)
                out[key] = float(-np.mean(
                    np.log(proba[np.arange(len(y)), y.astype(int)])))
            else:
                raise ValueError(f"unknown metric {m}")
        return out


class XGBoostRegressor(_XGBBase):
    _objective = "reg"


class XGBoostClassifier(_XGBBase):
    _objective = "clf"


class AutoXGBoost:
    """Hyperparameter search over the boosted-tree knobs via the shared
    search engine (reference: ``auto_xgb.AutoXGBRegressor/Classifier``
    through AutoEstimator)."""

    def __init__(self, task: str = "regression",
                 metric: Optional[str] = None,
                 n_parallel: int = 1,
                 fixed_config: Optional[Dict] = None):
        self.task = task
        self.metric = metric or ("mse" if task == "regression"
                                 else "accuracy")
        self.mode = "min" if self.metric in ("mse", "mae", "logloss") \
            else "max"
        self.n_parallel = n_parallel
        # reference: AutoXGB ctor kwargs like n_estimators/tree_method/
        # random_state are FIXED model params shared by every trial; the
        # searched space overrides them per-trial
        self.fixed_config = dict(fixed_config or {})
        self.best_model = None
        self.best_config: Optional[Dict] = None

    def fit(self, data, validation_data=None, search_space: Optional[Dict]
            = None, n_sampling: int = 4, seed: int = 0):
        from zoo_tpu.automl.search import LocalSearchEngine

        x, y = data
        vx, vy = validation_data if validation_data is not None else (x, y)
        cls = (XGBoostRegressor if self.task == "regression"
               else XGBoostClassifier)

        def trial(cfg: Dict) -> Dict:
            model = cls(config={**self.fixed_config, **cfg})
            model.fit(x, y)
            res = model.evaluate(vx, vy, metrics=(self.metric,))
            res["_model"] = model
            return res

        from zoo_tpu.automl import hp
        space = search_space or {
            "n_estimators": hp.choice([50, 100, 200]),
            "max_depth": hp.choice([3, 5, 7]),
            "learning_rate": hp.loguniform(0.01, 0.3),
        }
        eng = LocalSearchEngine(n_parallel=self.n_parallel)
        eng.compile(trial, space, n_sampling=n_sampling,
                    metric=self.metric, mode=self.mode, seed=seed)
        eng.run()
        best = eng.get_best_trial()
        self.best_config = dict(best.config)
        self.best_model = best.artifacts["_model"]
        return self

    def predict(self, x) -> np.ndarray:
        if self.best_model is None:
            raise RuntimeError("call fit() first")
        return self.best_model.predict(x)

    def get_best_model(self):
        return self.best_model


_AUTOXGB_INFRA_KWARGS = ("cpus_per_trial", "name", "logs_dir",
                         "remote_dir")


def _split_xgb_kwargs(kwargs: Dict) -> Dict:
    """Reference AutoXGB ctors mix infra args (dropped here) with fixed
    XGBoost params (forwarded into every trial's config)."""
    return {k: v for k, v in kwargs.items()
            if k not in _AUTOXGB_INFRA_KWARGS}


class AutoXGBRegressor(AutoXGBoost):
    """reference ``auto_xgb.AutoXGBRegressor`` — task pinned; extra
    kwargs become fixed per-trial XGBoost params."""

    def __init__(self, metric=None, n_parallel: int = 1, **xgb_params):
        super().__init__(task="regression", metric=metric,
                         n_parallel=n_parallel,
                         fixed_config=_split_xgb_kwargs(xgb_params))


class AutoXGBClassifier(AutoXGBoost):
    """reference ``auto_xgb.AutoXGBClassifier`` — task pinned; extra
    kwargs become fixed per-trial XGBoost params."""

    def __init__(self, metric=None, n_parallel: int = 1, **xgb_params):
        super().__init__(task="classification", metric=metric,
                         n_parallel=n_parallel,
                         fixed_config=_split_xgb_kwargs(xgb_params))
