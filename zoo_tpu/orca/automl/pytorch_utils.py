"""Reference ``zoo.orca.automl.pytorch_utils`` — the hyperparameter
key constants legacy model creators read from trial configs."""

LR_NAME = "lr"
DEFAULT_LR = 1e-3
