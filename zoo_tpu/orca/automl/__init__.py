from zoo_tpu.automl import hp  # noqa: F401  (reference: zoo.orca.automl.hp)
from zoo_tpu.orca.automl.auto_estimator import AutoEstimator

__all__ = ["AutoEstimator", "hp"]
