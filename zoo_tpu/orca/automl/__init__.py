from zoo_tpu.orca.automl.auto_estimator import AutoEstimator

__all__ = ["AutoEstimator"]
