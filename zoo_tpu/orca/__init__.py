from zoo_tpu.orca.common import (
    OrcaContext,
    init_orca_context,
    stop_orca_context,
)

__all__ = ["OrcaContext", "init_orca_context", "stop_orca_context"]
