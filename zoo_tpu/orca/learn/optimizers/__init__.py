from zoo_tpu.orca.learn.optimizers import schedule  # noqa: F401
from zoo_tpu.pipeline.api.keras.optimizers import (  # noqa: F401
    SGD, Adam, AdamWeightDecay, RMSprop, Adagrad, Adadelta, Adamax, LARS,
)
