"""Learning-rate schedule library.

Rebuild of the reference's schedule zoo
(``pyzoo/zoo/orca/learn/optimizers/schedule.py`` — Poly, Exponential, Step,
MultiStep, Plateau, Warmup, SequentialSchedule, Default, each wrapping the
BigDL JVM scheduler of the same name). The JVM schedulers mutate the optim
method's ``clr`` per iteration on the driver; here each schedule compiles to
a pure ``step -> lr`` callable that lives *inside* the jitted train step, so
the schedule advances on-device with zero host round-trips.

``Plateau`` is the one metric-driven (impure) schedule: it is evaluated
host-side between epochs and the new lr is injected into the optimizer state
(``optax.inject_hyperparams``) — see ``KerasNet.fit``.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import jax.numpy as jnp


class Scheduler:
    """Base: ``get_scheduler(base_lr)`` returns a ``step -> lr`` callable
    (the reference returns the wrapped JVM scheduler instead)."""

    def get_scheduler(self, base_lr: float) -> Callable:
        raise NotImplementedError


class Default(Scheduler):
    """Constant lr (reference ``schedule.py:89``)."""

    def get_scheduler(self, base_lr):
        return lambda step: jnp.full((), base_lr, jnp.float32)


class Poly(Scheduler):
    """lr = base_lr * (1 - iter/max_iteration)^power, clamped at zero
    (reference ``schedule.py:26``)."""

    def __init__(self, power, max_iteration):
        self.power = float(power)
        self.max_iteration = int(max_iteration)

    def get_scheduler(self, base_lr):
        def sched(step):
            frac = jnp.clip(1.0 - step / self.max_iteration, 0.0, 1.0)
            return base_lr * frac ** self.power
        return sched


class Exponential(Scheduler):
    """lr = base_lr * decay_rate^(iter/decay_step); ``stair_case`` floors the
    exponent (reference ``schedule.py:47``)."""

    def __init__(self, decay_step, decay_rate, stair_case=False):
        self.decay_step = int(decay_step)
        self.decay_rate = float(decay_rate)
        self.stair_case = bool(stair_case)

    def get_scheduler(self, base_lr):
        def sched(step):
            e = step / self.decay_step
            if self.stair_case:
                e = jnp.floor(e)
            return base_lr * self.decay_rate ** e
        return sched


class Step(Scheduler):
    """lr = base_lr * gamma^floor(iter/step_size) (reference
    ``schedule.py:67``)."""

    def __init__(self, step_size, gamma):
        self.step_size = int(step_size)
        self.gamma = float(gamma)

    def get_scheduler(self, base_lr):
        return lambda step: base_lr * self.gamma ** jnp.floor(
            step / self.step_size)


class MultiStep(Scheduler):
    """Step with non-uniform boundaries (reference ``schedule.py:167``)."""

    def __init__(self, step_sizes: List[int], gamma):
        self.step_sizes = [int(s) for s in step_sizes]
        self.gamma = float(gamma)

    def get_scheduler(self, base_lr):
        bounds = jnp.asarray(self.step_sizes)

        def sched(step):
            k = jnp.sum(step >= bounds)
            return base_lr * self.gamma ** k
        return sched


class Warmup(Scheduler):
    """lr = base_lr + delta * iteration — a gradual ramp, normally the first
    segment of a :class:`SequentialSchedule` (reference ``schedule.py:147``)."""

    def __init__(self, delta):
        self.delta = float(delta)

    def get_scheduler(self, base_lr):
        return lambda step: base_lr + self.delta * step


class SequentialSchedule(Scheduler):
    """Concatenate schedules, each running ``max_iteration`` steps
    (reference ``schedule.py:188``). ``iteration_per_epoch`` is kept for
    signature parity (the reference multiplies epoch-based triggers by it)."""

    def __init__(self, iteration_per_epoch: int = 1):
        self.iteration_per_epoch = int(iteration_per_epoch)
        self.schedules: List[Tuple[Scheduler, int]] = []

    def add(self, scheduler: Scheduler, max_iteration: int):
        self.schedules.append((scheduler, int(max_iteration)))
        return self

    def get_scheduler(self, base_lr):
        if not self.schedules:
            return Default().get_scheduler(base_lr)
        segs = [(s.get_scheduler(base_lr), n) for s, n in self.schedules]

        def sched(step):
            out = None
            offset = 0
            # piecewise select; the LAST segment extends to infinity
            for i, (fn, n) in enumerate(segs):
                local = fn(step - offset)
                if out is None:
                    out = local
                else:
                    out = jnp.where(step >= offset, local, out)
                offset += n
            return out
        return sched


class Plateau(Scheduler):
    """Reduce lr by ``factor`` when a monitored metric stops improving
    (reference ``schedule.py:109``). Metric-driven, so evaluated host-side
    between epochs; ``update(metric)`` returns the new lr, which the training
    loop injects into the optimizer state."""

    def __init__(self, monitor="Loss", factor=0.1, patience=10, mode="min",
                 epsilon=1e-4, cooldown=0, min_lr=0.0):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = int(patience)
        self.mode = mode
        self.epsilon = float(epsilon)
        self.cooldown = int(cooldown)
        self.min_lr = float(min_lr)
        self.base_lr = None  # bound by the optimizer facade
        self.current_lr = None
        self._best = None
        self._wait = 0
        self._cooldown_left = 0

    def bind(self, base_lr: float):
        """(Re)attach to an optimizer: resets ALL plateau state so a reused
        instance does not carry a previous run's best metric."""
        self.base_lr = float(base_lr)
        self.current_lr = float(base_lr)
        self._best = None
        self._wait = 0
        self._cooldown_left = 0
        return self

    def _improved(self, metric: float) -> bool:
        if self._best is None:
            return True
        if self.mode == "min":
            return metric < self._best - self.epsilon
        return metric > self._best + self.epsilon

    def update(self, metric: float) -> float:
        """Feed one epoch's monitored value; returns the lr to use next."""
        if self.current_lr is None:
            raise RuntimeError("Plateau.update before bind(base_lr)")
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self._wait = 0
        if self._improved(metric):
            self._best = metric
            self._wait = 0
        elif self._cooldown_left == 0:
            self._wait += 1
            if self._wait >= self.patience:
                self.current_lr = max(self.current_lr * self.factor,
                                      self.min_lr)
                self._cooldown_left = self.cooldown
                self._wait = 0
        return self.current_lr

    def get_scheduler(self, base_lr):
        # pure-schedule protocol: constant until update() injects a new lr
        self.bind(base_lr)
        return lambda step: jnp.full((), base_lr, jnp.float32)
