"""Optimized-inference Estimator (reference:
``pyzoo/zoo/orca/learn/openvino/estimator.py:25`` — the OpenVINO
estimator: distributed predict over XShards/arrays, ``fit`` refuses).

The reference's "optimized engine" was an OpenVINO IR compiled for VNNI;
the TPU equivalent is an XLA AOT-compiled executable inside
:class:`InferenceModel` — optionally int8-quantized onto the MXU (the
reference's int8 IR story). The estimator surface (``from_*`` loaders,
``predict`` over XShards / numpy / DataFrame, ``fit`` raising) matches
the reference so `openvino`-path user code ports by changing the import
and loader name.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from zoo_tpu.pipeline.inference.inference_model import InferenceModel


class InferenceEstimator:
    def __init__(self, model: InferenceModel,
                 batch_size: Optional[int] = None):
        self.model = model
        self.batch_size = batch_size

    # -- estimator surface (reference OpenvinoEstimator) ------------------
    def fit(self, *args, **kwargs):
        """reference: ``OpenvinoEstimator.fit`` raises — inference only."""
        raise NotImplementedError(
            "inference estimators cannot fit; load a trainable model "
            "through Estimator.from_keras / from_torch instead")

    def predict(self, data, batch_size: Optional[int] = None,
                feature_cols=None):
        """Predict over numpy / dict / XShards / DataFrame inputs
        (reference ``OpenvinoEstimator.predict`` over XShards/DataFrame).
        XShards input returns XShards of prediction dicts."""
        from zoo_tpu.orca.data.shard import LocalXShards
        from zoo_tpu.pipeline.api.keras.engine import data_utils

        bs = batch_size or self.batch_size or 256

        def _to_np(out):
            # multi-output models return a tuple of per-head arrays
            if isinstance(out, (list, tuple)):
                return [np.asarray(o) for o in out]
            return np.asarray(out)

        if isinstance(data, LocalXShards):
            def _predict_shard(shard):
                if isinstance(shard, np.ndarray):  # bare-array partitions
                    xs = [shard]
                else:
                    xs, _ = data_utils.to_xy_arrays(
                        LocalXShards([shard]), None, feature_cols, None)
                out = self.model.predict(
                    xs if len(xs) > 1 else xs[0], batch_size=bs)
                return {"prediction": _to_np(out)}
            return data.transform_shard(_predict_shard)
        xs, _ = data_utils.to_xy_arrays(data, None, feature_cols, None)
        return _to_np(self.model.predict(
            xs if len(xs) > 1 else xs[0], batch_size=bs))

    def evaluate(self, *args, **kwargs):
        raise NotImplementedError(
            "inference estimators expose predict() only (reference "
            "OpenVINO estimator behavior)")

    def get_model(self):
        return self.model


class Estimator:
    """Loader facade (reference ``Estimator.from_openvino``)."""

    @staticmethod
    def from_model(path: str, batch_size: Optional[int] = None,
                   quantize: bool = False,
                   concurrent_num: int = 4) -> InferenceEstimator:
        """Serialized zoo model; ``quantize=True`` = int8 MXU path (the
        reference's int8-IR analogue)."""
        im = InferenceModel(supported_concurrent_num=concurrent_num)
        im.load(path, batch_size=batch_size, quantize=quantize)
        return InferenceEstimator(im, batch_size)

    @staticmethod
    def from_tf(path: str, batch_size: Optional[int] = None,
                concurrent_num: int = 4) -> InferenceEstimator:
        im = InferenceModel(supported_concurrent_num=concurrent_num)
        im.load_tf(path, batch_size=batch_size)
        return InferenceEstimator(im, batch_size)

    @staticmethod
    def from_onnx(path, batch_size: Optional[int] = None,
                  concurrent_num: int = 4) -> InferenceEstimator:
        im = InferenceModel(supported_concurrent_num=concurrent_num)
        im.load_onnx(path, batch_size=batch_size)
        return InferenceEstimator(im, batch_size)

    @staticmethod
    def from_caffe(def_path, model_path,
                   batch_size: Optional[int] = None,
                   concurrent_num: int = 4) -> InferenceEstimator:
        im = InferenceModel(supported_concurrent_num=concurrent_num)
        im.load_caffe(def_path, model_path, batch_size=batch_size)
        return InferenceEstimator(im, batch_size)

    @staticmethod
    def from_openvino(*, model_path, batch_size: int = 0):
        """API-compatibility shim for reference code: OpenVINO IR cannot
        execute on TPU — the error names the supported migrations."""
        raise NotImplementedError(
            "OpenVINO IR is a CPU-specific format; on TPU export the "
            "original model instead and use Estimator.from_tf / "
            "from_onnx / from_model(..., quantize=True) for the "
            "optimized-int8 path")
