from zoo_tpu.orca.learn.inference.estimator import (  # noqa: F401
    Estimator,
    InferenceEstimator,
)

__all__ = ["Estimator", "InferenceEstimator"]
