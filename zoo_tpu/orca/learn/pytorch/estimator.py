"""Orca PyTorch Estimator — torch models trained TPU-native.

Rebuild of ``zoo.orca.learn.pytorch.estimator.Estimator.from_torch``
(reference: ``pyzoo/zoo/orca/learn/pytorch/estimator.py:108,261`` with its
two backends — Ray actors running DDP-over-gloo (``torch_runner.py:59``) or
the jep-embedded ``TorchModel`` on the BigDL fabric). Both reference paths
keep torch in the training loop; here the module is traced ONCE through
:mod:`zoo_tpu.bridges.fx_bridge` (torch.export → core-ATen graph → JAX
interpreter, weights imported by FQN), then the whole step runs as XLA on
the mesh — torch never executes on the hot path. The DDP allreduce becomes
the mesh ``data`` axis gradient psum.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from zoo_tpu.orca.learn.keras.estimator import KerasEstimator


def _convert_loss(loss):
    if loss is None or isinstance(loss, str):
        return loss
    if _is_torch_loss(loss):
        return _torch_loss_name(loss)
    if callable(loss):
        return loss
    raise ValueError(f"unsupported loss: {loss!r}")


def _is_torch_loss(obj) -> bool:
    try:
        import torch.nn as tnn
        return isinstance(obj, tnn.modules.loss._Loss)
    except Exception:
        return False


def _torch_loss_name(loss) -> str:
    import torch.nn as tnn
    table = {
        tnn.MSELoss: "mse",
        tnn.L1Loss: "mae",
        tnn.CrossEntropyLoss: "sparse_categorical_crossentropy_from_logits",
        tnn.BCELoss: "binary_crossentropy",
        tnn.BCEWithLogitsLoss: "binary_crossentropy_from_logits",
        tnn.NLLLoss: "nll",
    }
    for cls, name in table.items():
        if isinstance(loss, cls):
            return name
    raise ValueError(f"unsupported torch loss: {type(loss).__name__}")


def _convert_optimizer(optimizer, torch_model=None):
    """torch.optim instance → zoo optimizer with matching hyperparams."""
    from zoo_tpu.pipeline.api.keras import optimizers as zopt

    if optimizer is None:
        return "adam"
    if isinstance(optimizer, (str, zopt.Optimizer)):
        return optimizer
    try:
        import torch.optim as topt
        if isinstance(optimizer, topt.Optimizer):
            g = optimizer.param_groups[0]
            if isinstance(optimizer, topt.Adam):
                b1, b2 = g.get("betas", (0.9, 0.999))
                return zopt.Adam(lr=g["lr"], beta_1=b1, beta_2=b2,
                                 epsilon=g.get("eps", 1e-8))
            if isinstance(optimizer, topt.AdamW):
                b1, b2 = g.get("betas", (0.9, 0.999))
                return zopt.AdamWeightDecay(
                    lr=g["lr"], beta_1=b1, beta_2=b2,
                    weight_decay=g.get("weight_decay", 0.01))
            if isinstance(optimizer, topt.SGD):
                return zopt.SGD(lr=g["lr"],
                                momentum=g.get("momentum", 0.0),
                                nesterov=g.get("nesterov", False))
            if isinstance(optimizer, topt.RMSprop):
                return zopt.RMSprop(lr=g["lr"], rho=g.get("alpha", 0.99),
                                    epsilon=g.get("eps", 1e-8))
            if isinstance(optimizer, topt.Adagrad):
                return zopt.Adagrad(lr=g["lr"])
    except ImportError:
        pass
    raise ValueError(f"unsupported optimizer: {optimizer!r}")


class Estimator:
    @staticmethod
    def from_torch(*, model=None, optimizer=None, loss=None,
                   model_creator: Optional[Callable] = None,
                   optimizer_creator: Optional[Callable] = None,
                   loss_creator: Optional[Callable] = None,
                   config: Optional[dict] = None,
                   metrics=None, model_dir: Optional[str] = None,
                   backend: str = "tpu",
                   dtype_policy: str = "float32",
                   guard=None) -> "PyTorchEstimator":
        """reference signature: ``Estimator.from_torch(model=..., optimizer,
        loss, model_creator, ...)`` (``pytorch/estimator.py:33``). Either
        pass instances or the reference's creator functions (called with
        ``config``)."""
        cfg = dict(config or {})
        if model is None and model_creator is not None:
            model = model_creator(cfg)
        if model is None:
            raise ValueError("pass model= or model_creator=")
        if optimizer is None and optimizer_creator is not None:
            optimizer = optimizer_creator(model, cfg)
        if loss is None and loss_creator is not None:
            loss = loss_creator(cfg) if not _is_torch_loss(loss_creator) \
                else loss_creator
        return PyTorchEstimator(model, optimizer, loss, metrics=metrics,
                                model_dir=model_dir,
                                dtype_policy=dtype_policy, guard=guard)


class PyTorchEstimator(KerasEstimator):
    """Same surface as the keras estimator; conversion is lazy so the input
    shape can be inferred from the first fit/predict data."""

    def __init__(self, torch_model, optimizer, loss, metrics=None,
                 model_dir: Optional[str] = None,
                 dtype_policy: str = "float32", guard=None):
        self.torch_model = torch_model
        self._optimizer_arg = _convert_optimizer(optimizer)
        self._loss_arg = _convert_loss(loss)
        self._metrics_arg = metrics or []
        self._model_dir_arg = model_dir
        self._dtype_policy = dtype_policy
        self._converted = False
        super().__init__(model=None, model_dir=None, guard=guard)
        self.model_dir = model_dir

    def _ensure_converted(self, xs):
        if self._converted:
            return
        from zoo_tpu.bridges.fx_bridge import torch_to_graph_net
        from zoo_tpu.orca.learn.ckpt import CheckpointManager

        # trace with a tiny example batch (2 rows of each input)
        examples = [np.asarray(a[:2]) for a in xs]
        self.model = torch_to_graph_net(self.torch_model, examples)
        self.model.compile(optimizer=self._optimizer_arg,
                           loss=self._loss_arg or "mse",
                           metrics=self._metrics_arg,
                           dtype_policy=self._dtype_policy)
        if self._model_dir_arg:
            import os
            self._ckpt = CheckpointManager(
                os.path.join(self._model_dir_arg, "ckpts"))
            self.model.set_tensorboard(self._model_dir_arg, "summaries")
        # the manager and the converted model exist only now: rewire the
        # training guardian's checkpoint callbacks and attach it to the
        # freshly built KerasNet
        self._bind_guard()
        self._converted = True

    def _normalize(self, data, feature_cols, label_cols):
        from zoo_tpu.pipeline.api.keras.engine import data_utils
        xs, ys = data_utils.to_xy_arrays(data, None, feature_cols,
                                         label_cols)
        return xs, ys

    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols=None, label_cols=None, validation_data=None,
            checkpoint_trigger=None, shuffle: bool = True):
        xs, ys = self._normalize(data, feature_cols, label_cols)
        self._ensure_converted(xs)
        return super().fit({"x": xs if len(xs) > 1 else xs[0], "y": ys},
                           epochs=epochs, batch_size=batch_size,
                           validation_data=validation_data,
                           checkpoint_trigger=checkpoint_trigger,
                           shuffle=shuffle)

    def predict(self, data, batch_size: int = 256, feature_cols=None):
        xs, _ = self._normalize(data, feature_cols, None)
        self._ensure_converted(xs)
        return super().predict(xs if len(xs) > 1 else xs[0],
                               batch_size=batch_size)

    def evaluate(self, data, batch_size: int = 32, feature_cols=None,
                 label_cols=None):
        xs, ys = self._normalize(data, feature_cols, label_cols)
        self._ensure_converted(xs)
        return super().evaluate({"x": xs if len(xs) > 1 else xs[0],
                                 "y": ys}, batch_size=batch_size)

    def get_model(self):
        """Return the torch module with CURRENT (trained) weights written
        back — the reference returns the trained torch model too."""
        if self._converted and self.model is not None \
                and self.model.params is not None:
            self._export_weights_to_torch()
        return self.torch_model

    def _export_weights_to_torch(self):
        """Write trained weights back by torch FQN — the fx bridge keeps
        torch's own tensor layouts, so this is a plain state-dict copy."""
        import torch

        w = self.model.params["torch_graph"]["w"]
        named = dict(self.torch_model.named_parameters())
        with torch.no_grad():
            for fqn, val in w.items():
                if fqn in named:
                    t = named[fqn]
                    t.copy_(torch.from_numpy(
                        np.asarray(val).copy()).to(t.dtype))
