"""Orca PyTorch Estimator — torch models trained TPU-native.

Rebuild of ``zoo.orca.learn.pytorch.estimator.Estimator.from_torch``
(reference: ``pyzoo/zoo/orca/learn/pytorch/estimator.py:108,261`` with its
two backends — Ray actors running DDP-over-gloo (``torch_runner.py:59``) or
the jep-embedded ``TorchModel`` on the BigDL fabric). Both reference paths
keep torch in the training loop; here the module is converted ONCE through
:mod:`zoo_tpu.bridges.torch_bridge` into zoo_tpu layers (weights imported),
then the whole step runs as XLA on the mesh — torch never executes on the
hot path. The DDP allreduce becomes the mesh ``data`` axis gradient psum.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from zoo_tpu.orca.learn.keras.estimator import KerasEstimator


def _convert_loss(loss):
    if loss is None or isinstance(loss, str):
        return loss
    if _is_torch_loss(loss):
        return _torch_loss_name(loss)
    if callable(loss):
        return loss
    raise ValueError(f"unsupported loss: {loss!r}")


def _is_torch_loss(obj) -> bool:
    try:
        import torch.nn as tnn
        return isinstance(obj, tnn.modules.loss._Loss)
    except Exception:
        return False


def _torch_loss_name(loss) -> str:
    import torch.nn as tnn
    table = {
        tnn.MSELoss: "mse",
        tnn.L1Loss: "mae",
        tnn.CrossEntropyLoss: "sparse_categorical_crossentropy_from_logits",
        tnn.BCELoss: "binary_crossentropy",
        tnn.BCEWithLogitsLoss: "binary_crossentropy_from_logits",
        tnn.NLLLoss: "nll",
    }
    for cls, name in table.items():
        if isinstance(loss, cls):
            return name
    raise ValueError(f"unsupported torch loss: {type(loss).__name__}")


def _convert_optimizer(optimizer, torch_model=None):
    """torch.optim instance → zoo optimizer with matching hyperparams."""
    from zoo_tpu.pipeline.api.keras import optimizers as zopt

    if optimizer is None:
        return "adam"
    if isinstance(optimizer, (str, zopt.Optimizer)):
        return optimizer
    try:
        import torch.optim as topt
        if isinstance(optimizer, topt.Optimizer):
            g = optimizer.param_groups[0]
            if isinstance(optimizer, topt.Adam):
                b1, b2 = g.get("betas", (0.9, 0.999))
                return zopt.Adam(lr=g["lr"], beta_1=b1, beta_2=b2,
                                 epsilon=g.get("eps", 1e-8))
            if isinstance(optimizer, topt.AdamW):
                b1, b2 = g.get("betas", (0.9, 0.999))
                return zopt.AdamWeightDecay(
                    lr=g["lr"], beta_1=b1, beta_2=b2,
                    weight_decay=g.get("weight_decay", 0.01))
            if isinstance(optimizer, topt.SGD):
                return zopt.SGD(lr=g["lr"],
                                momentum=g.get("momentum", 0.0),
                                nesterov=g.get("nesterov", False))
            if isinstance(optimizer, topt.RMSprop):
                return zopt.RMSprop(lr=g["lr"], rho=g.get("alpha", 0.99),
                                    epsilon=g.get("eps", 1e-8))
            if isinstance(optimizer, topt.Adagrad):
                return zopt.Adagrad(lr=g["lr"])
    except ImportError:
        pass
    raise ValueError(f"unsupported optimizer: {optimizer!r}")


class Estimator:
    @staticmethod
    def from_torch(*, model=None, optimizer=None, loss=None,
                   model_creator: Optional[Callable] = None,
                   optimizer_creator: Optional[Callable] = None,
                   loss_creator: Optional[Callable] = None,
                   config: Optional[dict] = None,
                   metrics=None, model_dir: Optional[str] = None,
                   backend: str = "tpu") -> "PyTorchEstimator":
        """reference signature: ``Estimator.from_torch(model=..., optimizer,
        loss, model_creator, ...)`` (``pytorch/estimator.py:33``). Either
        pass instances or the reference's creator functions (called with
        ``config``)."""
        cfg = dict(config or {})
        if model is None and model_creator is not None:
            model = model_creator(cfg)
        if model is None:
            raise ValueError("pass model= or model_creator=")
        if optimizer is None and optimizer_creator is not None:
            optimizer = optimizer_creator(model, cfg)
        if loss is None and loss_creator is not None:
            loss = loss_creator(cfg) if not _is_torch_loss(loss_creator) \
                else loss_creator
        return PyTorchEstimator(model, optimizer, loss, metrics=metrics,
                                model_dir=model_dir)


class PyTorchEstimator(KerasEstimator):
    """Same surface as the keras estimator; conversion is lazy so the input
    shape can be inferred from the first fit/predict data."""

    def __init__(self, torch_model, optimizer, loss, metrics=None,
                 model_dir: Optional[str] = None):
        self.torch_model = torch_model
        self._optimizer_arg = _convert_optimizer(optimizer)
        self._loss_arg = _convert_loss(loss)
        self._metrics_arg = metrics or []
        self._model_dir_arg = model_dir
        self._converted = False
        super().__init__(model=None, model_dir=None)
        self.model_dir = model_dir

    def _ensure_converted(self, xs):
        if self._converted:
            return
        from zoo_tpu.bridges.torch_bridge import torch_to_keras_model
        from zoo_tpu.orca.learn.ckpt import CheckpointManager

        input_shape = xs[0].shape[1:] if len(xs) == 1 else None
        if input_shape is None:
            raise ValueError("torch bridge supports single-input models")
        self.model = torch_to_keras_model(self.torch_model, input_shape)
        self.model.compile(optimizer=self._optimizer_arg,
                           loss=self._loss_arg or "mse",
                           metrics=self._metrics_arg)
        if self._model_dir_arg:
            import os
            self._ckpt = CheckpointManager(
                os.path.join(self._model_dir_arg, "ckpts"))
            self.model.set_tensorboard(self._model_dir_arg, "summaries")
        self._converted = True

    def _normalize(self, data, feature_cols, label_cols):
        from zoo_tpu.pipeline.api.keras.engine import data_utils
        xs, ys = data_utils.to_xy_arrays(data, None, feature_cols,
                                         label_cols)
        return xs, ys

    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols=None, label_cols=None, validation_data=None,
            checkpoint_trigger=None, shuffle: bool = True):
        xs, ys = self._normalize(data, feature_cols, label_cols)
        self._ensure_converted(xs)
        return super().fit({"x": xs if len(xs) > 1 else xs[0], "y": ys},
                           epochs=epochs, batch_size=batch_size,
                           validation_data=validation_data,
                           checkpoint_trigger=checkpoint_trigger,
                           shuffle=shuffle)

    def predict(self, data, batch_size: int = 256, feature_cols=None):
        xs, _ = self._normalize(data, feature_cols, None)
        self._ensure_converted(xs)
        return super().predict(xs if len(xs) > 1 else xs[0],
                               batch_size=batch_size)

    def evaluate(self, data, batch_size: int = 32, feature_cols=None,
                 label_cols=None):
        xs, ys = self._normalize(data, feature_cols, label_cols)
        self._ensure_converted(xs)
        return super().evaluate({"x": xs if len(xs) > 1 else xs[0],
                                 "y": ys}, batch_size=batch_size)

    def get_model(self):
        """Return the torch module with CURRENT (trained) weights written
        back — the reference returns the trained torch model too."""
        if self._converted and self.model is not None \
                and self.model.params is not None:
            self._export_weights_to_torch()
        return self.torch_model

    def _export_weights_to_torch(self):
        import torch

        import jax
        params = jax.tree_util.tree_map(np.asarray, self.model.params)
        from zoo_tpu.bridges.torch_bridge import convert_torch_module
        # re-walk in the same order to pair torch modules with our layers
        idx = 0
        import torch.nn as tnn

        def walk(m):
            nonlocal idx
            if isinstance(m, tnn.Sequential):
                for c in m:
                    walk(c)
                return
            key = self.model._key_of(self.model.layers[idx]) \
                if idx < len(self.model.layers) else None
            if isinstance(m, tnn.Linear):
                p = params[key]
                with torch.no_grad():
                    m.weight.copy_(torch.from_numpy(np.ascontiguousarray(np.asarray(p["W"]).T)))
                    if m.bias is not None and "b" in p:
                        m.bias.copy_(torch.from_numpy(np.asarray(p["b"]).copy()))
                idx += 1
                return
            if isinstance(m, tnn.Conv2d):
                p = params[key]
                with torch.no_grad():
                    m.weight.copy_(torch.from_numpy(np.ascontiguousarray(
                        np.transpose(np.asarray(p["W"]), (3, 2, 0, 1)))))
                    if m.bias is not None and "b" in p:
                        m.bias.copy_(torch.from_numpy(np.asarray(p["b"]).copy()))
                idx += 1
                return
            if isinstance(m, tnn.Embedding):
                with torch.no_grad():
                    m.weight.copy_(torch.from_numpy(
                        np.asarray(params[key]["E"]).copy()))
                idx += 1
                return
            if isinstance(m, (tnn.BatchNorm1d, tnn.LayerNorm, tnn.LSTM,
                              tnn.GRU, tnn.MaxPool2d, tnn.AvgPool2d,
                              tnn.Flatten, tnn.Dropout)) or \
                    type(m).__name__ in ("ReLU", "Sigmoid", "Tanh",
                                         "Softmax", "GELU", "SiLU",
                                         "LeakyReLU", "ELU", "Identity"):
                # stateless or not-yet-exported stateful layers advance the
                # cursor only if the bridge emitted a layer for them
                if not isinstance(m, tnn.Identity):
                    idx += 1
                return
            idx += 1

        walk(self.torch_model)
