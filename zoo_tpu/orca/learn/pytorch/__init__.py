from zoo_tpu.orca.learn.pytorch.estimator import Estimator, PyTorchEstimator

__all__ = ["Estimator", "PyTorchEstimator"]
