"""GANEstimator: alternating generator/discriminator training.

Rebuild of the reference's GAN fabric (``tfpark/gan/gan_estimator.py`` +
Scala ``GanOptimMethod.scala:77``, which interleaves ``dSteps``
discriminator updates with ``gSteps`` generator updates inside one
optimizer). Here both sub-steps are a SINGLE jitted function — generator
forward, discriminator real/fake passes, both parameter updates — so the
whole adversarial iteration is one XLA program on the mesh.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def _bce_logits(logits, target: float):
    z = logits.reshape(-1)
    # stable sigmoid BCE against a constant target
    return jnp.mean(jnp.maximum(z, 0) - z * target +
                    jnp.log1p(jnp.exp(-jnp.abs(z))))


class GANEstimator:
    """``generator``: KerasNet noise→sample; ``discriminator``: KerasNet
    sample→logit (linear output). Optimizers are zoo/optax optimizers."""

    def __init__(self, generator, discriminator,
                 g_optimizer="adam", d_optimizer="adam",
                 noise_dim: int = 64, d_steps: int = 1, g_steps: int = 1,
                 guard=None):
        from zoo_tpu.orca.learn.guard import TrainingGuard
        from zoo_tpu.pipeline.api.keras.optimizers import get_optimizer

        self.g = generator
        self.d = discriminator
        self.g_tx = get_optimizer(g_optimizer).make()
        self.d_tx = get_optimizer(d_optimizer).make()
        self.noise_dim = int(noise_dim)
        self.d_steps = int(d_steps)
        self.g_steps = int(g_steps)
        self._jit_step = None
        self._state = None
        # training guardian: adversarial training is the classic NaN
        # factory (saturated discriminators); a bad iteration folds away
        # whole (docs/fault_tolerance.md). No checkpoint manager here,
        # so divergence escalates straight to TrainingDiverged.
        if guard is False:
            self._guard = None
        else:
            self._guard = guard if guard is not None \
                else TrainingGuard.from_env(name="gan")

    # -- the jitted adversarial iteration ---------------------------------
    def _build_step(self):
        import optax

        from zoo_tpu.pipeline.api.keras.engine.topology import _merge_state

        g, d = self.g, self.d
        g_tx, d_tx = self.g_tx, self.d_tx
        d_steps, g_steps = self.d_steps, self.g_steps

        # gradients flow through TRAINABLE subtrees only; non-trainable
        # state (BatchNorm running stats) stays fixed during adversarial
        # training (documented: use LayerNorm-style nets for stats-free
        # training, as most GAN recipes do)
        def d_loss_fn(d_tr, d_st, g_tr, g_st, real, z):
            fake = g._forward(_merge_state(g_tr, g_st), [z], training=True,
                              rng=None, collect=None)
            dp = _merge_state(d_tr, d_st)
            real_logit = d._forward(dp, [real], training=True, rng=None,
                                    collect=None)
            fake_logit = d._forward(dp, [jax.lax.stop_gradient(fake)],
                                    training=True, rng=None, collect=None)
            return _bce_logits(real_logit, 1.0) + _bce_logits(fake_logit,
                                                              0.0)

        def g_loss_fn(g_tr, g_st, d_tr, d_st, z):
            fake = g._forward(_merge_state(g_tr, g_st), [z], training=True,
                              rng=None, collect=None)
            fake_logit = d._forward(_merge_state(d_tr, d_st), [fake],
                                    training=True, rng=None, collect=None)
            return _bce_logits(fake_logit, 1.0)  # non-saturating

        guard = self._guard if (self._guard is not None
                                and self._guard.active) else None

        def step(state, rng, real):
            if guard is not None:
                state, gstate = state
            g_tr, g_st, d_tr, d_st, g_opt, d_opt = state
            old = state
            d_loss = g_loss = 0.0
            d_grads = g_grads = None
            for _ in range(d_steps):
                rng, zk = jax.random.split(rng)
                z = jax.random.normal(zk, (real.shape[0], self.noise_dim))
                d_loss, d_grads = jax.value_and_grad(d_loss_fn)(
                    d_tr, d_st, g_tr, g_st, real, z)
                upd, d_opt = d_tx.update(d_grads, d_opt, d_tr)
                d_tr = optax.apply_updates(d_tr, upd)
            for _ in range(g_steps):
                rng, zk = jax.random.split(rng)
                z = jax.random.normal(zk, (real.shape[0], self.noise_dim))
                g_loss, g_grads = jax.value_and_grad(g_loss_fn)(
                    g_tr, g_st, d_tr, d_st, z)
                upd, g_opt = g_tx.update(g_grads, g_opt, g_tr)
                g_tr = optax.apply_updates(g_tr, upd)
            new = (g_tr, g_st, d_tr, d_st, g_opt, d_opt)
            if guard is not None:
                # one non-finite sub-loss/grad poisons the whole
                # adversarial iteration: fold it away as a unit
                ok = guard.grad_norm_ok(d_loss + g_loss,
                                        (d_grads, g_grads))
                new = guard.health_fold(ok, new, old)
                gstate = guard.gstate_update(gstate, ok)
                return ((new, gstate), rng,
                        jnp.where(ok, d_loss, 0.0),
                        jnp.where(ok, g_loss, 0.0))
            return (new, rng, d_loss, g_loss)

        return jax.jit(step, donate_argnums=(0, 1))

    # -- API ---------------------------------------------------------------
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            seed: int = 0) -> Dict[str, list]:
        real = np.asarray(data["x"] if isinstance(data, dict) else data,
                          np.float32)
        self.g.build(jax.random.PRNGKey(seed),
                     [(None, self.noise_dim)])
        self.d.build(jax.random.PRNGKey(seed + 1),
                     [(None,) + real.shape[1:]])
        if batch_size > len(real):
            raise ValueError(f"batch_size ({batch_size}) exceeds dataset "
                             f"size ({len(real)})")
        from zoo_tpu.pipeline.api.keras.engine.topology import (
            _merge_state,
            _split_state,
        )

        if self._state is None:
            g_tr, g_st = _split_state(self.g._place(self.g.params))
            d_tr, d_st = _split_state(self.d._place(self.d.params))
            self._state = (g_tr, g_st, d_tr, d_st,
                           self.g_tx.init(g_tr), self.d_tx.init(d_tr))
        if self._jit_step is None:
            self._jit_step = self._build_step()
        rng = jax.random.PRNGKey(seed + 2)
        n = (len(real) // batch_size) * batch_size
        history = {"d_loss": [], "g_loss": []}
        guard = self._guard if (self._guard is not None
                                and self._guard.active) else None
        if guard is not None:
            guard.begin_fit()
            guard.install_signal_handler()
            self._state = (self._state, guard.device_init())
        bad_seen = 0
        try:
            for epoch in range(epochs):
                # permute the FULL set, then drop the ragged tail —
                # different rows fall off each epoch, so no row is
                # permanently excluded
                perm = np.random.RandomState(seed + epoch).permutation(
                    len(real))[:n]
                d_sum = g_sum = None
                steps = 0
                for lo in range(0, n, batch_size):
                    batch = jnp.asarray(real[perm[lo:lo + batch_size]])
                    self._state, rng, d_loss, g_loss = self._jit_step(
                        self._state, rng, batch)
                    d_sum = d_loss if d_sum is None else d_sum + d_loss
                    g_sum = g_loss if g_sum is None else g_sum + g_loss
                    steps += 1
                good = steps
                if guard is not None:
                    g = jax.device_get(self._state[1])
                    act = guard.on_boundary(
                        bad_total=int(g["bad"]), streak=int(g["streak"]),
                        window_loss=float(np.asarray(d_sum + g_sum)),
                        window_steps=steps,
                        global_step=(epoch + 1) * steps, epoch=epoch)
                    good = max(steps - (int(g["bad"]) - bad_seen), 1)
                    bad_seen = int(g["bad"])
                    if act == "rollback":
                        # no checkpoint manager on the GAN path: this
                        # raises TrainingDiverged unless the caller
                        # bound restore/save callbacks on the guard
                        state, _aux, _lr = guard.rollback()
                        self._state = (state["gan_state"],
                                       guard.device_init())
                        bad_seen = 0
                        continue
                    if act == "preempt":
                        guard.preempt_checkpoint(
                            step=(epoch + 1) * steps)
                history["d_loss"].append(float(np.asarray(d_sum)) / good)
                history["g_loss"].append(float(np.asarray(g_sum)) / good)
        finally:
            if guard is not None:
                guard.uninstall_signal_handler()
                self._state = self._state[0]
        g_tr, g_st, d_tr, d_st = self._state[:4]
        self.g.params = _merge_state(g_tr, g_st)
        self.d.params = _merge_state(d_tr, d_st)
        return history

    def generate(self, n: int, seed: int = 0) -> np.ndarray:
        z = np.asarray(jax.random.normal(jax.random.PRNGKey(seed),
                                         (n, self.noise_dim)))
        return self.g.predict(z, batch_size=n)
