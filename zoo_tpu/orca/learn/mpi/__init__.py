"""MPI fabric shim (reference: ``orca/learn/mpi/mpi_estimator.py:28`` —
mpirun-launched training with plasma-staged partitions).

The mpirun-one-process-per-host pattern maps directly onto the TPU
launch story: ``python -m zoo_tpu.orca.bootstrap`` locally,
``scripts/run_tpu_pod.sh`` on a pod (one process per host,
``jax.distributed`` as the rendezvous). The reference import path
resolves and redirects."""


class MPIEstimator:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "No MPI on TPU — the equivalent launch is one supervised "
            "process per host: python -m zoo_tpu.orca.bootstrap "
            "--nproc N train.py (dev box) or scripts/run_tpu_pod.sh "
            "(pod); inside, use any orca Estimator")
