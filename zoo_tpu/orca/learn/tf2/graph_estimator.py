"""TF1 graph-mode TRAINING on the TPU fabric.

Rebuild of the reference's flagship TF1 training path —
``Estimator.from_graph`` (``pyzoo/zoo/orca/learn/tf/estimator.py:291``)
and the TFOptimizer machinery it drives
(``pyzoo/zoo/tfpark/tf_optimizer.py:464,514``): a user-built TF1 graph
(placeholder inputs/labels, variables, scalar loss tensor) trained
distributed. The reference exports the session graph to the JVM/BigDL
fabric; here the graph's variables are captured as a JAX params pytree
(``bridges/tf_graph.capture_trainable_graph``), the interpreted loss is
differentiated with ``jax.grad``, and the update step is one jitted XLA
program — params replicated over the mesh, batches sharded on the data
axes, gradient all-reduce inserted by XLA (no parameter server, no
NCCL).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp


def _convert_tf1_optimizer(opt):
    """Translate a ``tf.compat.v1.train.Optimizer`` (the reference's
    calling convention for ``from_graph``) into the matching zoo
    optimizer, reading the hyperparameters off the instance."""
    from zoo_tpu.pipeline.api.keras import optimizers as zopt

    def hp(*names, default=None):
        for nm in names:
            v = getattr(opt, nm, None)
            if v is None:
                continue
            try:
                return float(v)
            except (TypeError, ValueError):
                raise NotImplementedError(
                    f"{type(opt).__name__}.{nm} is not a plain float "
                    "(a schedule/tensor?); pass a zoo optimizer with an "
                    "explicit learningrate_schedule instead")
        return default

    name = type(opt).__name__
    if name == "GradientDescentOptimizer":
        return zopt.SGD(lr=hp("_learning_rate", default=0.01))
    if name == "MomentumOptimizer":
        return zopt.SGD(lr=hp("_learning_rate", default=0.01),
                        momentum=hp("_momentum", default=0.0),
                        nesterov=bool(getattr(opt, "_use_nesterov",
                                              False)))
    if name == "AdamOptimizer":
        return zopt.Adam(lr=hp("_lr", "_learning_rate", default=0.001),
                         beta_1=hp("_beta1", default=0.9),
                         beta_2=hp("_beta2", default=0.999),
                         epsilon=hp("_epsilon", default=1e-8))
    if name == "AdagradOptimizer":
        return zopt.Adagrad(lr=hp("_learning_rate", default=0.01))
    if name == "RMSPropOptimizer":
        return zopt.RMSprop(lr=hp("_learning_rate", default=0.001),
                            rho=hp("_decay", default=0.9))
    raise NotImplementedError(
        f"tf.train optimizer {name} has no zoo mapping; pass one of "
        "zoo.orca.learn.optimizers (SGD/Adam/Adagrad/RMSprop/...) or a "
        "string name")


def _resolve_optimizer(optimizer):
    if optimizer is None:
        return "adam"
    try:
        import tensorflow as tf
        if isinstance(optimizer, tf.compat.v1.train.Optimizer):
            return _convert_tf1_optimizer(optimizer)
    except ImportError:
        pass
    return optimizer


def _clip_value_transform(lo: float, hi: float):
    import optax

    def update(updates, state, params=None):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, lo, hi), updates), state

    return optax.GradientTransformation(lambda params: (), update)


class GraphTrainer:
    """The jitted train/predict/evaluate loop over a
    :class:`~zoo_tpu.bridges.tf_graph.TrainableTFGraph`."""

    def __init__(self, trainable, optimizer=None,
                 clip_norm: Optional[float] = None,
                 clip_value=None, guard=None):
        import optax

        from zoo_tpu.pipeline.api.keras.optimizers import get_optimizer

        self.t = trainable
        self.guard = guard  # TrainingGuard (orca/learn/guard.py)
        tx = get_optimizer(_resolve_optimizer(optimizer)).make()
        chain = []
        if clip_norm is not None:
            if clip_norm <= 0:
                raise ValueError("clip_norm must be positive")
            chain.append(optax.clip_by_global_norm(float(clip_norm)))
        if clip_value is not None:
            if isinstance(clip_value, (int, float)):
                if clip_value <= 0:
                    raise ValueError("clip_value must be positive")
                clip_value = (-float(clip_value), float(clip_value))
            if not (isinstance(clip_value, tuple) and len(clip_value) == 2):
                raise ValueError(
                    "clip_value: positive number or (min, max) tuple")
            chain.append(_clip_value_transform(*clip_value))
        chain.append(tx)
        self.tx = optax.chain(*chain) if len(chain) > 1 else tx
        self.params = {k: jnp.asarray(v)
                       for k, v in self.t.params.items()}
        self.opt_state = None
        self._jit_step = None
        self._jit_fwd = None
        self._jit_loss = None

    # -- placement --------------------------------------------------------
    @staticmethod
    def _mesh():
        from zoo_tpu.common.context import get_runtime_context
        ctx = get_runtime_context(required=False)
        return getattr(ctx, "mesh", None) if ctx is not None else None

    def _place_params(self):
        mesh = self._mesh()
        if mesh is None:
            return
        from zoo_tpu.parallel.mesh import replicated_sharding
        sh = replicated_sharding(mesh)
        self.params = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sh), self.params)
        if self.opt_state is not None:
            self.opt_state = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sh) if hasattr(a, "ndim")
                else a, self.opt_state)

    def _put_batch(self, arrs: Sequence[np.ndarray]):
        mesh = self._mesh()
        if mesh is None:
            return [jnp.asarray(a) for a in arrs]
        from zoo_tpu.parallel.mesh import (
            batch_sharding,
            data_axes,
            replicated_sharding,
        )
        dsize = int(np.prod([mesh.shape[a] for a in data_axes(mesh)]))
        return [jax.device_put(
            a, batch_sharding(mesh, a.ndim)
            if np.asarray(a).shape[0] % dsize == 0
            else replicated_sharding(mesh)) for a in arrs]

    # -- jitted programs --------------------------------------------------
    def _active_guard(self):
        g = self.guard
        return g if g is not None and g.active else None

    def _build_step(self):
        import optax

        n_in = len(self.t.input_names)
        guard = self._active_guard()

        def step(params, opt_state, *data):
            if guard is not None:
                opt_state, gstate = opt_state
            inputs, labels = data[:n_in], data[n_in:]

            def lf(p):
                return self.t.loss_fn(p, inputs, labels)

            loss, grads = jax.value_and_grad(lf)(params)
            old_params, old_opt = params, opt_state
            upd, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, upd)
            if guard is not None:
                # in-step health guard, same contract as topology's fit
                # step: a non-finite loss/grad-norm folds the whole
                # update away; the counter pair rides the opt carry
                ok = guard.grad_norm_ok(loss, grads)
                params = guard.health_fold(ok, params, old_params)
                opt_state = guard.health_fold(ok, opt_state, old_opt)
                return (params,
                        (opt_state, guard.gstate_update(gstate, ok)),
                        jnp.where(ok, loss, 0.0))
            return params, opt_state, loss

        return jax.jit(step, donate_argnums=(0, 1))

    # -- API --------------------------------------------------------------
    def fit(self, xs: List[np.ndarray], ys: List[np.ndarray],
            epochs: int = 1, batch_size: int = 32, shuffle: bool = True,
            seed: int = 0,
            max_steps: Optional[int] = None) -> Dict[str, List[float]]:
        if not self.params:
            raise ValueError(
                "the captured graph has no trainable variables — nothing "
                "to train (build the model under "
                "tf.compat.v1.get_variable/tf.Variable)")
        if self.opt_state is None:
            self.opt_state = self.tx.init(self.params)
        self._place_params()
        if self._jit_step is None:
            self._jit_step = self._build_step()
        from zoo_tpu.parallel.mesh import validate_batch_size
        mesh = self._mesh()
        if mesh is not None:
            batch_size = validate_batch_size(batch_size, mesh)
        n = int(xs[0].shape[0])
        rng = np.random.default_rng(seed)
        history: Dict[str, List[float]] = {"loss": []}
        steps_done = 0
        guard = self._active_guard()
        wrapped = False
        if guard is not None:
            guard.begin_fit()
            self.opt_state = (self.opt_state, guard.device_init())
            wrapped = True
        bad_seen = 0
        try:
            for _ in range(int(epochs)):
                order = rng.permutation(n) if shuffle else np.arange(n)
                losses = []
                # drop the ragged tail batch like the reference fabric
                # does (a second compiled shape for <1 batch of data
                # isn't worth it)
                usable = max(n - n % batch_size, batch_size) \
                    if n >= batch_size else n
                for lo in range(0, usable, batch_size):
                    if max_steps is not None and steps_done >= max_steps:
                        break
                    idx = order[lo:lo + batch_size]
                    batch = self._put_batch(
                        [np.asarray(a)[idx] for a in (*xs, *ys)])
                    self.params, self.opt_state, loss = self._jit_step(
                        self.params, self.opt_state, *batch)
                    losses.append(loss)
                    steps_done += 1
                if guard is not None:
                    # epoch-boundary guard check (graph models dispatch
                    # per step, so the counter read syncs nothing extra)
                    g = jax.device_get(self.opt_state[1])
                    window = float(np.sum([np.asarray(v)
                                           for v in losses])) \
                        if losses else 0.0
                    act = guard.on_boundary(
                        bad_total=int(g["bad"]), streak=int(g["streak"]),
                        window_loss=window, window_steps=len(losses),
                        global_step=steps_done)
                    bad_epoch = int(g["bad"]) - bad_seen
                    bad_seen = int(g["bad"])
                    if act == "rollback":
                        state, aux, _lr = guard.rollback()
                        self.params = {k: jnp.asarray(v) for k, v in
                                       state["params"].items()}
                        inner = aux if aux is not None \
                            else self.tx.init(self.params)
                        self.opt_state = (inner, guard.device_init())
                        bad_seen = 0
                        continue  # retrain the epoch from the snapshot
                    if act == "preempt":
                        guard.preempt_checkpoint(step=steps_done)
                    if losses:
                        history["loss"].append(
                            window / max(len(losses) - bad_epoch, 1))
                elif losses:
                    history["loss"].append(
                        float(np.mean([np.asarray(v) for v in losses])))
                if max_steps is not None and steps_done >= max_steps:
                    break
        finally:
            if wrapped:
                self.opt_state = self.opt_state[0]
        return history

    def predict(self, xs: List[np.ndarray], batch_size: int = 256):
        if self._jit_fwd is None:
            self._jit_fwd = jax.jit(
                lambda p, *i: self.t.forward(p, i))
        n = int(xs[0].shape[0])
        outs = []
        for lo in range(0, n, batch_size):
            chunk = [np.asarray(a)[lo:lo + batch_size] for a in xs]
            real = chunk[0].shape[0]
            if real < batch_size and lo > 0:
                chunk = [np.concatenate(
                    [a, np.repeat(a[:1], batch_size - real, axis=0)])
                    for a in chunk]
            out = self._jit_fwd(self.params, *self._put_batch(chunk))
            first = out[0] if isinstance(out, tuple) else out
            outs.append(np.asarray(first)[:real])
        return np.concatenate(outs, axis=0)

    def evaluate(self, xs: List[np.ndarray], ys: List[np.ndarray],
                 batch_size: int = 32) -> Dict[str, float]:
        if self._jit_loss is None:
            n_in = len(self.t.input_names)

            def lm(p, *data):
                inputs, labels = data[:n_in], data[n_in:]
                out = {}
                if self.t.loss_ref is not None:
                    out["loss"] = self.t.loss_fn(p, inputs, labels)
                out.update(self.t.metrics_fn(p, inputs, labels))
                return out

            self._jit_loss = jax.jit(lm)
        n = int(xs[0].shape[0])
        acc: Dict[str, list] = {}
        for lo in range(0, n, batch_size):
            batch = self._put_batch(
                [np.asarray(a)[lo:lo + batch_size] for a in (*xs, *ys)])
            for k, v in self._jit_loss(self.params, *batch).items():
                acc.setdefault(k, []).append(
                    (np.asarray(v), batch[0].shape[0]))
        return {k: float(sum(float(np.mean(v)) * w for v, w in pairs)
                         / sum(w for _, w in pairs))
                for k, pairs in acc.items()}

    def numpy_params(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}


class TFGraphEstimator:
    """Orca Estimator over a live TF1 graph — the
    ``Estimator.from_graph`` surface (reference
    ``orca/learn/tf/estimator.py:291``): fit/predict/evaluate +
    checkpoint save/load, with trained weights written back into the
    user's session so their saver/export flow keeps working."""

    def __init__(self, *, inputs, outputs=None, labels=None, loss=None,
                 optimizer=None, metrics=None, clip_norm=None,
                 clip_value=None, updates=None, sess=None,
                 model_dir=None, guard=None):
        from zoo_tpu.bridges.tf_graph import capture_trainable_graph

        inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
            else [inputs]
        labels = [] if labels is None else (
            list(labels) if isinstance(labels, (list, tuple))
            else [labels])
        outputs = [] if outputs is None else (
            list(outputs) if isinstance(outputs, (list, tuple))
            else [outputs])
        if updates:
            import logging
            logging.getLogger(__name__).warning(
                "from_graph(updates=...): moving-stat update ops are "
                "captured frozen at conversion time in the TPU rebuild "
                "(the interpreted graph is pure); running stats will not "
                "advance during training")
        self.trainable, self.sess, self._tf_vars = \
            capture_trainable_graph(inputs=inputs, labels=labels,
                                    loss=loss, outputs=outputs,
                                    metrics=metrics, sess=sess)
        self.trainer = GraphTrainer(self.trainable, optimizer,
                                    clip_norm=clip_norm,
                                    clip_value=clip_value)
        self.model_dir = model_dir
        self._epoch = 0
        # training guardian (docs/fault_tolerance.md): attach before the
        # first fit so the jitted step is built guarded
        from zoo_tpu.orca.learn.guard import TrainingGuard
        if guard is False:
            self._guard = None
        else:
            self._guard = guard if guard is not None \
                else TrainingGuard.from_env(name="tf_graph")
        if self._guard is not None:
            self.trainer.guard = self._guard
            if model_dir:
                import os
                import pickle

                def _restore():
                    path = os.path.join(model_dir, "tf_graph_ckpt.pkl")
                    with open(path, "rb") as f:
                        return pickle.load(f), None

                self._guard.bind(
                    save_fn=lambda: (self._write_back(),
                                     self.save_checkpoint()),
                    restore_fn=_restore,
                    quarantine_path=os.path.join(
                        model_dir, "guard", "quarantine.jsonl"))

    # -- data -------------------------------------------------------------
    def _norm(self, data, feature_cols, label_cols, need_y):
        from zoo_tpu.pipeline.api.keras.engine import data_utils
        xs, ys = data_utils.to_xy_arrays(data, None, feature_cols,
                                         label_cols)
        xs = list(xs) if isinstance(xs, (list, tuple)) else [xs]
        ys = [] if ys is None else (
            list(ys) if isinstance(ys, (list, tuple)) else [ys])
        if need_y and not ys:
            raise ValueError("this call needs labels; got features only")
        n_in = len(self.trainable.input_names)
        n_lb = len(self.trainable.label_names)
        if len(xs) == n_in + n_lb and not ys and n_lb:
            xs, ys = xs[:n_in], xs[n_in:]
        if len(xs) != n_in:
            raise ValueError(
                f"graph has {n_in} input placeholder(s) "
                f"{self.trainable.input_names}, got {len(xs)} feature "
                "array(s)")
        if need_y and len(ys) != n_lb:
            raise ValueError(
                f"graph has {n_lb} label placeholder(s) "
                f"{self.trainable.label_names}, got {len(ys)} label "
                "array(s)")
        return xs, ys

    # -- orca estimator surface ------------------------------------------
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols=None, label_cols=None, validation_data=None,
            checkpoint_trigger=None, shuffle: bool = True):
        xs, ys = self._norm(data, feature_cols, label_cols, need_y=True)
        val = None
        if validation_data is not None:
            val = self._norm(validation_data, feature_cols, label_cols,
                             need_y=True)
        hist: Dict[str, List[float]] = {}
        if self._guard is not None:
            self._guard.install_signal_handler()
        try:
            for _ in range(int(epochs)):
                h = self.trainer.fit(xs, ys, epochs=1,
                                     batch_size=batch_size,
                                     shuffle=shuffle, seed=self._epoch)
                for k, v in h.items():
                    hist.setdefault(k, []).extend(v)
                self._epoch += 1
                if val is not None:
                    for k, v in self.trainer.evaluate(
                            *val, batch_size=batch_size).items():
                        hist.setdefault(f"val_{k}", []).append(v)
                if self.model_dir and checkpoint_trigger is not None and \
                        checkpoint_trigger.fire_on_epoch(self._epoch):
                    self._write_back()
                    self.save_checkpoint()
        finally:
            if self._guard is not None:
                self._guard.uninstall_signal_handler()
        self._write_back()
        if self.model_dir:
            self.save_checkpoint()
        return hist

    def predict(self, data, batch_size: int = 4, feature_cols=None,
                **_):
        xs, _ys = self._norm(data, feature_cols, None, need_y=False)
        return self.trainer.predict(xs, batch_size=max(batch_size, 1))

    def evaluate(self, data, batch_size: int = 32, feature_cols=None,
                 label_cols=None):
        xs, ys = self._norm(data, feature_cols, label_cols, need_y=True)
        return self.trainer.evaluate(xs, ys, batch_size=batch_size)

    # -- session round-trip ----------------------------------------------
    def _write_back(self):
        from zoo_tpu.bridges.tf_graph import write_back_variables
        write_back_variables(self.sess, self._tf_vars,
                             self.trainer.numpy_params())

    def get_model(self):
        """The live TF1 session, trained weights written back — what the
        reference's ``sess`` holds after fit."""
        return self.sess

    # -- checkpoints ------------------------------------------------------
    def save_checkpoint(self, path: Optional[str] = None):
        import os
        import pickle
        path = path or os.path.join(self.model_dir or ".",
                                    "tf_graph_ckpt.pkl")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump({"params": self.trainer.numpy_params(),
                         "epoch": self._epoch}, f)
        return path

    def load_checkpoint(self, path: str):
        import pickle
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.trainer.params = {k: jnp.asarray(v)
                               for k, v in state["params"].items()}
        # optimizer moments belong to the PREVIOUS trajectory; reusing
        # them against restored weights corrupts the first updates
        self.trainer.opt_state = None
        self._epoch = int(state.get("epoch", 0))
        self._write_back()

    def save_tf_checkpoint(self, path: str):
        """reference ``save_tf_checkpoint`` — a real tf.train.Saver
        checkpoint of the (written-back) session variables."""
        import tensorflow as tf
        self._write_back()
        with self.sess.graph.as_default():
            saver = tf.compat.v1.train.Saver(self._tf_vars)
            saver.save(self.sess, path)
        return path
