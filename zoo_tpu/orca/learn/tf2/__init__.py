from zoo_tpu.orca.learn.tf2.estimator import Estimator  # noqa: F401
