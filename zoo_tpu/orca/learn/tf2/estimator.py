"""Orca TF2 Estimator — tf.keras model creators trained TPU-native.

Rebuild of ``zoo.orca.learn.tf2.estimator.Estimator.from_keras``
(reference: ``pyzoo/zoo/orca/learn/tf2/estimator.py:86``): the user hands
over a ``model_creator(config) -> compiled tf.keras model`` (plus optional
``data_creator(config, batch_size) -> tf.data.Dataset``); the reference
replays the creator on every Ray worker under
``MultiWorkerMirroredStrategy`` (``tf_runner.py:226,280-323``). Here the
creator runs ONCE, the model is converted through
:mod:`zoo_tpu.bridges.keras_bridge` (configs + weights + compile settings),
and training is the jitted sharded XLA step — the mesh replaces TF's
collective-ops ring.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from zoo_tpu.orca.learn.keras.estimator import KerasEstimator


def _convert_optimizer(kopt):
    """keras optimizer instance → zoo optimizer with matching hyperparams."""
    from zoo_tpu.pipeline.api.keras import optimizers as zopt

    if kopt is None:
        return "adam"
    cfg = {}
    try:
        cfg = kopt.get_config()
    except Exception:
        pass
    name = str(cfg.get("name", type(kopt).__name__)).lower()
    lr = float(cfg.get("learning_rate", 0.001)) \
        if np.isscalar(cfg.get("learning_rate", 0.001)) else 0.001
    if "adamw" in name or "adam_w" in name:
        return zopt.AdamWeightDecay(lr=lr,
                                    weight_decay=float(
                                        cfg.get("weight_decay", 0.01)
                                        or 0.01))
    if "adamax" in name:
        return zopt.Adamax(lr=lr)
    if "adagrad" in name:
        return zopt.Adagrad(lr=lr)
    if "adadelta" in name:
        return zopt.Adadelta(lr=lr)
    if "adam" in name:
        return zopt.Adam(lr=lr, beta_1=float(cfg.get("beta_1", 0.9)),
                         beta_2=float(cfg.get("beta_2", 0.999)),
                         epsilon=float(cfg.get("epsilon", 1e-7)))
    if "rmsprop" in name:
        return zopt.RMSprop(lr=lr, rho=float(cfg.get("rho", 0.9)))
    if "sgd" in name:
        return zopt.SGD(lr=lr, momentum=float(cfg.get("momentum", 0.0)),
                        nesterov=bool(cfg.get("nesterov", False)))
    return zopt.Adam(lr=lr)


_LOSS_MAP = {
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mae", "mae": "mae",
    "binary_crossentropy": "binary_crossentropy",
    "categorical_crossentropy": "categorical_crossentropy",
    "sparse_categorical_crossentropy": "sparse_categorical_crossentropy",
    "hinge": "hinge", "squared_hinge": "squared_hinge",
    "kl_divergence": "kld", "kld": "kld", "poisson": "poisson",
}


def _convert_loss(kloss):
    if kloss is None:
        return "mse"
    name = kloss if isinstance(kloss, str) else (
        getattr(kloss, "name", None) or type(kloss).__name__)
    key = str(name).lower()
    # keras-3 class names like SparseCategoricalCrossentropy
    snake = "".join(("_" + ch.lower()) if ch.isupper() else ch
                    for ch in str(name)).lstrip("_")
    for cand in (key, snake):
        if cand in _LOSS_MAP:
            return _LOSS_MAP[cand]
    raise ValueError(f"unsupported keras loss: {name!r}")


def _convert_metrics(kmodel) -> list:
    names = []
    try:  # keras 3 records the user's compile() args here
        cc = kmodel.get_compile_config() or {}
        for m in cc.get("metrics") or []:
            names.append(str(getattr(m, "name", None) or
                             (m.get("config", {}).get("name")
                              if isinstance(m, dict) else m)))
    except Exception:
        pass
    for m in getattr(kmodel, "metrics", []) or []:
        names.append(str(getattr(m, "name", m)))
    out = []
    for name in names:
        n = name.lower()
        if "acc" in n and "accuracy" not in out:
            out.append("accuracy")
        elif n in ("mae", "mean_absolute_error") and "mae" not in out:
            out.append("mae")
        elif n in ("mse", "mean_squared_error") and "mse" not in out:
            out.append("mse")
    return out


class Estimator:
    @staticmethod
    def from_graph(*, inputs=None, outputs=None, labels=None, loss=None,
                   optimizer=None, metrics=None, clip_norm=None,
                   clip_value=None, updates=None, sess=None,
                   model_dir=None, backend="bigdl", guard=None, **_):
        """reference ``orca/learn/tf/estimator.py:291`` — train a
        user-built TF1 graph (placeholder inputs/labels + scalar loss
        tensor). The reference drives the session graph on the JVM
        fabric; here the graph's variables are captured as a JAX params
        pytree and trained with ``jax.grad`` of the interpreted loss on
        the mesh (``graph_estimator.TFGraphEstimator``)."""
        if inputs is None:
            raise ValueError("from_graph requires inputs= (the graph's "
                             "input placeholder tensors)")
        from zoo_tpu.orca.learn.tf2.graph_estimator import (
            TFGraphEstimator,
        )
        return TFGraphEstimator(inputs=inputs, outputs=outputs,
                                labels=labels, loss=loss,
                                optimizer=optimizer, metrics=metrics,
                                clip_norm=clip_norm,
                                clip_value=clip_value, updates=updates,
                                sess=sess, model_dir=model_dir,
                                guard=guard)

    @staticmethod
    def from_keras(*, model_creator: Callable,
                   config: Optional[dict] = None,
                   model_dir: Optional[str] = None,
                   backend: str = "tpu",
                   workers_per_node: int = 1,
                   compile_args: Optional[dict] = None,
                   guard=None) -> "TF2Estimator":
        """reference signature: ``Estimator.from_keras(model_creator=...,
        config=..., workers_per_node=..., backend="tf2")``
        (``tf2/estimator.py:38``).

        ``guard``: training guardian override (``TrainingGuard`` instance
        or False); defaults to the env-configured guard — see
        docs/fault_tolerance.md."""
        return TF2Estimator(model_creator, config=config,
                            model_dir=model_dir,
                            compile_args=compile_args, guard=guard)


class TF2Estimator(KerasEstimator):
    def __init__(self, model_creator: Callable, config: Optional[dict],
                 model_dir: Optional[str] = None,
                 compile_args: Optional[dict] = None, guard=None):
        self.config = dict(config or {})
        kmodel = model_creator(self.config)
        self._kmodel = kmodel
        from zoo_tpu.bridges.keras_bridge import convert_keras_model

        zmodel = convert_keras_model(kmodel)
        ca = compile_args or {}
        zmodel.compile(
            optimizer=ca.get("optimizer",
                             _convert_optimizer(
                                 getattr(kmodel, "optimizer", None))),
            loss=ca.get("loss",
                        _convert_loss(getattr(kmodel, "loss", None))),
            metrics=ca.get("metrics", _convert_metrics(kmodel)))
        super().__init__(zmodel, model_dir=model_dir, guard=guard)

    # -- data adapters -----------------------------------------------------
    def _materialize(self, data, batch_size):
        """Accept the reference's data forms: creator function, tf.data
        Dataset, XShards / dict / arrays. Dataset conversion delegates to
        the shared loader path in ``data_utils``."""
        if callable(data) and not isinstance(data, (list, tuple, dict)):
            data = data(self.config, batch_size)  # reference data_creator
        from zoo_tpu.pipeline.api.keras.engine.data_utils import (
            _foreign_batches, to_xy_arrays)
        if _foreign_batches(data) is not None:
            xs, ys = to_xy_arrays(data)
            out = {"x": xs if len(xs) > 1 else xs[0]}
            if ys is not None:
                out["y"] = ys
            return out
        return data

    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols: Optional[Sequence[str]] = None,
            label_cols: Optional[Sequence[str]] = None,
            validation_data=None, checkpoint_trigger=None,
            shuffle: bool = True, **kw):
        data = self._materialize(data, batch_size)
        if validation_data is not None:
            validation_data = self._materialize(validation_data, batch_size)
        return super().fit(data, epochs=epochs, batch_size=batch_size,
                           feature_cols=feature_cols, label_cols=label_cols,
                           validation_data=validation_data,
                           checkpoint_trigger=checkpoint_trigger,
                           shuffle=shuffle, **kw)

    def predict(self, data, batch_size: int = 256, feature_cols=None):
        return super().predict(self._materialize(data, batch_size),
                               batch_size=batch_size,
                               feature_cols=feature_cols)

    def evaluate(self, data, batch_size: int = 32, feature_cols=None,
                 label_cols=None):
        return super().evaluate(self._materialize(data, batch_size),
                                batch_size=batch_size,
                                feature_cols=feature_cols,
                                label_cols=label_cols)

    def get_model(self):
        """Return the tf.keras model with trained weights written back
        (the reference returns the worker-0 keras model)."""
        self._export_weights_to_keras()
        return self._kmodel

    def _export_weights_to_keras(self):
        import jax

        zmodel = self.model
        params = jax.tree_util.tree_map(np.asarray, zmodel.params)
        for z in zmodel.layers:
            key = zmodel._key_of(z)
            p = params.get(key)
            if not p:
                continue
            kl = self._keras_layer_for(z)
            if kl is None:
                continue
            t = type(kl).__name__
            if t == "Dense" or t.startswith("Conv"):
                w = [p["W"]] + ([p["b"]] if "b" in p else [])
                kl.set_weights(w)
            elif t == "Embedding":
                kl.set_weights([p["E"]])
            elif t == "BatchNormalization":
                kl.set_weights([p["gamma"], p["beta"],
                                p["stats"]["mean"], p["stats"]["var"]])
            elif t == "LayerNormalization":
                kl.set_weights([p["gamma"], p["beta"]])
            elif t in ("LSTM", "GRU"):
                kl.set_weights([p["W"], p["U"]] +
                               ([p["b"]] if "b" in p else []))

    def _keras_layer_for(self, zoo_layer):
        """Pair zoo layers with keras layers by parametrized-layer order."""
        zoo_param = [l for l in self.model.layers
                     if self.model.params.get(self.model._key_of(l))]
        keras_param = [l for l in self._kmodel.layers if l.get_weights()]
        try:
            idx = zoo_param.index(zoo_layer)
            return keras_param[idx]
        except (ValueError, IndexError):
            return None
