"""MXNet fabric shim (reference: ``orca/learn/mxnet/estimator.py`` —
Ray actors split into kvstore servers and workers).

MXNet has no TPU backend and the kvstore parameter server maps onto the
same XLA-collective fabric as everything else (SURVEY §2.11). The
reference import path resolves and redirects."""


class Estimator:
    @staticmethod
    def from_mxnet(*args, **kwargs):
        raise NotImplementedError(
            "MXNet has no TPU backend. Port the model to a supported "
            "frontend: orca.learn.pytorch Estimator.from_torch traces "
            "any torch module; gluon models usually translate 1:1")
