"""MXNet fabric shim (reference: ``orca/learn/mxnet/estimator.py`` —
Ray actors split into kvstore servers and workers).

MXNet has no TPU backend and the kvstore parameter server maps onto the
same XLA-collective fabric as everything else (SURVEY §2.11). The
reference import path resolves and redirects."""


class Estimator:
    @staticmethod
    def from_mxnet(*args, **kwargs):
        raise NotImplementedError(
            "MXNet has no TPU backend. Port the model to a supported "
            "frontend: orca.learn.pytorch Estimator.from_torch traces "
            "any torch module; gluon models usually translate 1:1")


def create_config(log_interval=10, optimizer="sgd",
                  optimizer_params=None, seed=None, **extra_config):
    """reference ``mxnet/utils.py`` ``create_config`` — builds the
    trainer config dict MXNet estimators consumed. Kept so reference
    scripts reach the redirect above with their config intact."""
    config = {"log_interval": log_interval, "optimizer": optimizer,
              "optimizer_params": optimizer_params or {}}
    if seed is not None:
        config["seed"] = seed
    config.update(extra_config)
    return config
