# zoo-lint: jax-free
"""Training guardian: step-level numeric health, rollback, preemption.

The reference's only in-job recovery is retry-the-whole-job from the
latest snapshot (``Topology.scala:1255-1337``); PR 1 lifted that to
process supervision (``run_elastic``). This module handles the failure
at the layer where it happens, with the cheap fix tried before the
expensive one before the catastrophic one:

1. **In-step health guard** — the jitted train step checks
   ``isfinite(loss)`` and the gradient global-norm *inside* the XLA
   computation. On a bad step params and optimizer state pass through
   unchanged (``where``-folded — no host sync, no branch); a device-side
   ``(bad, streak)`` counter rides the optimizer-state carry and is read
   only at superbatch boundaries. Offending windows are quarantined to a
   JSONL journal plus obs counters.
2. **Divergence rollback** — ``max_skips`` consecutive skipped steps, or
   a window loss beyond ``spike_factor``× the rolling-window median,
   restores the last verified :class:`CheckpointManager` step (optional
   LR backoff on resume), bounded by ``rollback_budget`` before raising
   :class:`TrainingDiverged`.
3. **Preemption-safe exit** — SIGTERM (or the ``$ZOO_PREEMPT`` signal;
   the TPU maintenance-event notice) requests checkpoint-and-exit at the
   next step boundary, coordinated across hosts over the JAX
   coordination-service KV store so every process stops at the SAME
   global step; the process exits :data:`PREEMPT_EXIT_CODE` (75,
   EX_TEMPFAIL), which ``run_elastic`` treats as resume-don't-retry.

This module must import WITHOUT jax (``scripts/check_guard.py`` drives
the escalation ladder jax-free); everything device-side imports jax
lazily.

Knobs (all overridable per-instance via :class:`GuardConfig`):

=============================  =============================================
``ZOO_GUARD``                  "0" disables the guard estimators attach
``ZOO_GUARD_MAX_SKIPS``        consecutive skipped steps before rollback (8)
``ZOO_GUARD_SPIKE_FACTOR``     window-loss spike multiple vs rolling median
                               triggering rollback (10.0)
``ZOO_GUARD_WINDOW``           rolling-loss window length in boundaries (32)
``ZOO_GUARD_MIN_WINDOW``       boundaries before spike detection arms (5)
``ZOO_GUARD_ROLLBACK_BUDGET``  rollbacks before TrainingDiverged (3)
``ZOO_GUARD_LR_BACKOFF``       LR multiplier applied on rollback resume (0.5)
``ZOO_GUARD_CHECK_EVERY``      read the device counter every N boundaries (1)
``ZOO_GUARD_MAX_GNORM``        optional hard gradient-norm ceiling (off)
``ZOO_GUARD_QUARANTINE``       JSONL journal path (default
                               <model_dir>/guard/quarantine.jsonl)
``ZOO_PREEMPT``                preemption signal name ("SIGTERM"; "0"/"none"
                               disables the handler)
=============================  =============================================
"""

from __future__ import annotations

import json
import logging
import os
import signal as _signal
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from zoo_tpu.obs.metrics import counter, gauge

logger = logging.getLogger(__name__)

#: Exit code of a preemption-triggered graceful exit (EX_TEMPFAIL).
#: ``ProcessMonitor``/``run_elastic`` treat it as "checkpointed, relaunch
#: me at the same world size and resume" — never as a crash.
PREEMPT_EXIT_CODE = 75

_nonfinite_steps = counter(
    "zoo_guard_nonfinite_steps_total",
    "Training steps skipped by the in-step health guard (non-finite loss "
    "or gradient norm; params/opt state passed through unchanged)")
_rollbacks = counter(
    "zoo_guard_rollbacks_total",
    "Divergence rollbacks: restores from the last verified checkpoint "
    "triggered by skip streaks or loss spikes")
_preempt_ckpts = counter(
    "zoo_guard_preempt_checkpoints_total",
    "Coordinated checkpoint-and-exit sequences completed after a "
    "preemption signal")
_diverged = counter(
    "zoo_guard_diverged_total",
    "Fits abandoned with TrainingDiverged (rollback budget exhausted or "
    "no checkpoint to restore)")
_rolling_loss = gauge(
    "zoo_guard_rolling_loss",
    "Mean per-step training loss over the guard's most recent boundary "
    "window (skipped steps excluded)")


class TrainingDiverged(RuntimeError):
    """The guard's escalation ladder is exhausted: skip didn't help,
    the rollback budget is spent (or there is nothing to restore), and
    the loss is still not trainable."""


class EpochRolledBack(RuntimeError):
    """A mid-epoch guard rollback wiped every step of the epoch: the
    restored state has made no progress and there is no honest loss to
    report. The Estimator's retry perimeter treats this like any other
    recoverable failure — restore the latest verified checkpoint and
    retrain the lost epoch (the epoch counter did not advance) —
    while bare ``model.fit`` callers see a loud typed failure instead
    of a fabricated loss value."""


class Preempted(SystemExit):
    """Raised after a preemption-triggered checkpoint. Subclasses
    ``SystemExit`` with :data:`PREEMPT_EXIT_CODE`, so a worker script
    needs no handling at all — letting it propagate exits the process
    with the code ``run_elastic`` recognizes as resume-don't-retry.
    ``except Exception`` retry perimeters never swallow it."""

    def __init__(self, step: int):
        super().__init__(PREEMPT_EXIT_CODE)
        self.step = int(step)


# the shared ZOO_* knob parsers (zoo_tpu.util.resilience is jax-free,
# so importing them keeps this module's no-jax contract)
from zoo_tpu.util.resilience import env_float as _env_float  # noqa: E402
from zoo_tpu.util.resilience import env_int as _env_int  # noqa: E402


class GuardConfig:
    """Escalation-ladder knobs; every field defaults from ``ZOO_GUARD_*``
    env so supervised workers configure through their launcher."""

    def __init__(self, enabled: Optional[bool] = None,  # zoo-lint: config-parse
                 max_skips: Optional[int] = None,
                 spike_factor: Optional[float] = None,
                 window: Optional[int] = None,
                 min_window: Optional[int] = None,
                 rollback_budget: Optional[int] = None,
                 lr_backoff: Optional[float] = None,
                 check_every: Optional[int] = None,
                 max_grad_norm: Optional[float] = None,
                 preempt_signal: Optional[str] = None):
        self.enabled = (os.environ.get("ZOO_GUARD", "1") != "0"
                        if enabled is None else bool(enabled))
        self.max_skips = (_env_int("ZOO_GUARD_MAX_SKIPS", 8)
                          if max_skips is None else int(max_skips))
        self.spike_factor = (_env_float("ZOO_GUARD_SPIKE_FACTOR", 10.0)
                             if spike_factor is None
                             else float(spike_factor))
        self.window = (_env_int("ZOO_GUARD_WINDOW", 32)
                       if window is None else int(window))
        self.min_window = (_env_int("ZOO_GUARD_MIN_WINDOW", 5)
                           if min_window is None else int(min_window))
        self.rollback_budget = (_env_int("ZOO_GUARD_ROLLBACK_BUDGET", 3)
                                if rollback_budget is None
                                else int(rollback_budget))
        self.lr_backoff = (_env_float("ZOO_GUARD_LR_BACKOFF", 0.5)
                           if lr_backoff is None else float(lr_backoff))
        self.check_every = max(1, _env_int("ZOO_GUARD_CHECK_EVERY", 1)
                               if check_every is None
                               else int(check_every))
        env_gn = os.environ.get("ZOO_GUARD_MAX_GNORM")
        self.max_grad_norm = (float(env_gn) if env_gn and
                              max_grad_norm is None
                              else max_grad_norm)
        sig = (os.environ.get("ZOO_PREEMPT", "SIGTERM")
               if preempt_signal is None else preempt_signal)
        self.preempt_signal = None if str(sig).lower() in (
            "", "0", "none", "off") else str(sig)


def _world() -> Tuple[int, int]:
    """(process_count, process_index); (1, 0) when jax is not already
    loaded (no jax ⇒ no cluster) or uninitialized. Reads
    ``sys.modules`` instead of importing so the jax-free script path
    stays jax-free."""
    jax = sys.modules.get("jax")
    if jax is None:
        return 1, 0
    try:
        return jax.process_count(), jax.process_index()
    except Exception:
        return 1, 0


def _kv_client():
    try:
        from zoo_tpu.obs.coordination import coordination_client
        return coordination_client()
    except Exception:
        return None


class TrainingGuard:
    """Host-side controller of the three guard layers.

    The fit loop owns the device state (a ``{"bad", "streak"}`` int32
    pair created by :meth:`device_init`, updated inside the jitted step
    by the topology/graph/gan step builders) and calls
    :meth:`on_boundary` at superbatch boundaries with its host-read
    values. The guard decides ``None`` (keep going), ``"rollback"``
    (call :meth:`rollback`, splice the returned state in), or
    ``"preempt"`` (call :meth:`preempt_checkpoint`, which saves and
    raises :class:`Preempted`).

    ``save_fn``/``restore_fn`` come from the owning estimator:
    ``save_fn()`` snapshots its current train state through its
    :class:`CheckpointManager`; ``restore_fn()`` returns
    ``(state_dict, aux)`` from the last verified step. Either may be
    None (no ``model_dir``): layers 1 and 3 still work; layer 2 then
    escalates straight to :class:`TrainingDiverged`.

    Multi-process decisions need no message exchange: the step math is
    SPMD-identical on every process, so bad counters, streaks, and
    window losses agree bit-for-bit and every rank reaches the same
    verdict at the same boundary. Only preemption (which starts from a
    single-host signal) coordinates over the KV store.
    """

    _seq = 0  # per-process fit counter; advances in SPMD lockstep

    def __init__(self, config: Optional[GuardConfig] = None,  # zoo-lint: config-parse
                 save_fn: Optional[Callable[[], None]] = None,
                 restore_fn: Optional[Callable[[], Tuple[Any, Any]]] = None,
                 quarantine_path: Optional[str] = None,
                 name: str = "fit"):
        self.config = config or GuardConfig()
        self.save_fn = save_fn
        self.restore_fn = restore_fn
        self.quarantine_path = quarantine_path or \
            os.environ.get("ZOO_GUARD_QUARANTINE")
        self.name = name
        # host-visible tallies (tests/scripts read these);
        # nonfinite_steps is CUMULATIVE across fits — the device counter
        # restarts at zero each fit/rollback, tracked by _bad_seen
        self.nonfinite_steps = 0
        self._bad_seen = 0
        self.rollbacks = 0
        self.preempt_checkpoints = 0
        self._window: deque = deque(maxlen=max(2, self.config.window))
        self._lock = threading.Lock()
        # preemption machinery
        self._preempt_flag = threading.Event()
        self._prev_handler = None
        self._installed_signum = None
        self._install_depth = 0
        self._kv_prefix: Optional[str] = None
        self._preempt_published = False
        self._preempt_acked = False
        self._preempt_target: Optional[int] = None
        self._all_can_restore: Optional[bool] = None
        self._boundary_calls = 0

    # -- wiring ------------------------------------------------------------
    @classmethod
    def from_env(cls, **kwargs) -> Optional["TrainingGuard"]:
        """A guard configured from ``ZOO_GUARD_*``, or None when
        ``ZOO_GUARD=0`` — estimators attach this by default."""
        cfg = kwargs.pop("config", None) or GuardConfig()
        if not cfg.enabled:
            return None
        return cls(config=cfg, **kwargs)

    def bind(self, save_fn=None, restore_fn=None, quarantine_path=None):
        """(Re)attach the checkpoint callbacks — estimators that build
        their CheckpointManager lazily (pytorch) rebind here."""
        if save_fn is not None:
            self.save_fn = save_fn
        if restore_fn is not None:
            self.restore_fn = restore_fn
        if quarantine_path is not None and self.quarantine_path is None:
            self.quarantine_path = quarantine_path
        return self

    @property
    def active(self) -> bool:
        return self.config.enabled

    @property
    def preempt_requested(self) -> bool:
        return self._preempt_flag.is_set()

    # -- device-side pieces (lazy jax) ------------------------------------
    def device_init(self):
        """Fresh ``{"bad", "streak"}`` int32 counters for the optimizer-
        state carry."""
        import jax.numpy as jnp
        return {"bad": jnp.zeros((), jnp.int32),
                "streak": jnp.zeros((), jnp.int32)}

    def health_fold(self, ok, new_tree, old_tree):
        """``where``-fold two identically-structured pytrees on the
        scalar predicate ``ok`` — the no-host-sync skip primitive. Used
        inside jitted steps only."""
        import jax
        import jax.numpy as jnp
        return jax.tree_util.tree_map(
            lambda n, o: jnp.where(ok, n, o), new_tree, old_tree)

    def gstate_update(self, gstate, ok):
        """Advance the device counter pair for one step."""
        import jax.numpy as jnp
        bad = (~ok).astype(jnp.int32)
        return {"bad": gstate["bad"] + bad,
                "streak": jnp.where(ok, 0, gstate["streak"] + 1)}

    def grad_norm_ok(self, loss, grads):
        """The in-step health predicate: finite loss AND finite gradient
        global-norm (AND under the optional hard ceiling)."""
        import jax
        import jax.numpy as jnp
        gnorm_sq = sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree_util.tree_leaves(grads)) \
            if jax.tree_util.tree_leaves(grads) else jnp.zeros(())
        ok = jnp.isfinite(loss) & jnp.isfinite(gnorm_sq)
        if self.config.max_grad_norm:
            ok = ok & (gnorm_sq <= self.config.max_grad_norm ** 2)
        return ok

    # -- fit lifecycle ----------------------------------------------------
    def begin_fit(self):
        """Called by the fit loop before the first step: (multi-process)
        allocates this fit's KV namespace and exchanges restore
        capability. Signal-handler install is the guard OWNER's job
        (estimator/forecaster fit entry, via
        :meth:`install_signal_handler`) — once per outer fit, not once
        per epoch.

        Preemption state deliberately survives across fits: the request
        rides a JOB-global KV namespace (ranks drift in wall time, so a
        rank one epoch ahead must still see a request published from an
        earlier fit; global step counts stay monotonic and comparable),
        and the whole job exits once it is honored."""
        self._boundary_calls = 0
        self._bad_seen = 0  # fresh device counters accompany each fit
        pc, pid = _world()
        TrainingGuard._seq += 1
        self._kv_prefix = f"zoo/guard/{TrainingGuard._seq}/"
        if pc > 1:
            client = _kv_client()
            if client is not None:
                try:
                    client.key_value_set(
                        f"{self._kv_prefix}cap/{pid}",
                        "1" if self.restore_fn else "0")
                    caps = [client.blocking_key_value_get(
                        f"{self._kv_prefix}cap/{p}", 30_000)
                        for p in range(pc)]
                    self._all_can_restore = all(c == "1" for c in caps)
                except Exception as e:  # degraded: act alone
                    logger.warning("guard capability exchange failed "
                                   "(%s); rollback decisions fall back "
                                   "to local capability", e)
                    self._all_can_restore = None

    def end_fit(self):
        self.uninstall_signal_handler()

    # -- signal handling ---------------------------------------------------
    def _signum(self) -> Optional[int]:
        name = self.config.preempt_signal
        if not name:
            return None
        if name.isdigit():
            return int(name)
        return getattr(_signal, name if name.startswith("SIG")
                       else "SIG" + name, None)

    def install_signal_handler(self):
        """Idempotent (depth-counted); silently skipped off the main
        thread — a concurrent-AutoML trial fit must not fight over
        process signals."""
        signum = self._signum()
        if signum is None:
            return
        self._install_depth += 1
        if self._install_depth > 1:
            return
        try:
            self._prev_handler = _signal.signal(
                signum, lambda s, f: self.request_preempt())
            self._installed_signum = signum
        except ValueError:  # not the main thread
            self._prev_handler = None
            self._installed_signum = None

    def uninstall_signal_handler(self):
        self._install_depth = max(0, self._install_depth - 1)
        if self._install_depth == 0 and self._installed_signum is not None:
            try:
                _signal.signal(self._installed_signum,
                               self._prev_handler or _signal.SIG_DFL)
            except ValueError:
                pass
            self._installed_signum = None

    def request_preempt(self):
        """Ask for checkpoint-and-exit at the next step boundary (the
        signal handler's body; tests call it directly)."""
        if not self._preempt_flag.is_set():
            logger.warning(
                "%s: preemption requested — checkpoint-and-exit at the "
                "next step boundary", self.name)
        self._preempt_flag.set()

    # -- the boundary decision --------------------------------------------
    def on_boundary(self, bad_total: int, streak: int,
                    window_loss: float, window_steps: int,
                    global_step: int, epoch: int = 0,
                    batch_hint: Optional[Tuple[int, int]] = None
                    ) -> Optional[str]:
        """One superbatch boundary. ``bad_total``/``streak`` are the
        host-read device counters; ``window_loss`` is the (sanitized —
        skipped steps contribute 0) loss sum since the previous boundary
        over ``window_steps`` steps. Returns None, ``"rollback"`` or
        ``"preempt"``."""
        self._boundary_calls += 1
        # bad_total restarts at zero each fit/rollback (fresh device
        # counters); _bad_seen is the per-incarnation baseline, while
        # nonfinite_steps accumulates across the guard's whole life
        delta = bad_total - self._bad_seen
        self._bad_seen = bad_total
        if delta > 0:
            _nonfinite_steps.inc(delta)
            self.nonfinite_steps += delta
            self._journal({
                "event": "nonfinite_steps", "epoch": int(epoch),
                "global_step": int(global_step), "bad_in_window": delta,
                "bad_total": self.nonfinite_steps, "streak": int(streak),
                "batch_lo": None if batch_hint is None
                else int(batch_hint[0]),
                "batch_hi": None if batch_hint is None
                else int(batch_hint[1]),
            })
            logger.warning(
                "%s: skipped %d non-finite step(s) in the last window "
                "(total %d, streak %d) at step %d", self.name, delta,
                self.nonfinite_steps, streak, global_step)
        good = window_steps - delta
        mean = None
        if good > 0:
            mean = window_loss / good
            _rolling_loss.set(mean)
        spike = (mean is not None and len(self._window) >=
                 self.config.min_window and
                 mean > self.config.spike_factor *
                 max(self._rolling_median(), 1e-12))
        if mean is not None and not spike:
            self._window.append(mean)
        if self._preempt_step(global_step):
            return "preempt"
        if streak >= self.config.max_skips:
            logger.error(
                "%s: %d consecutive steps skipped (>= max_skips=%d) — "
                "escalating to rollback", self.name, streak,
                self.config.max_skips)
            return "rollback"
        if spike:
            logger.error(
                "%s: window loss %.6g spiked beyond %gx the rolling "
                "median %.6g — escalating to rollback", self.name, mean,
                self.config.spike_factor, self._rolling_median())
            return "rollback"
        return None

    def _rolling_median(self) -> float:
        vals = sorted(self._window)
        if not vals:
            return 0.0
        mid = len(vals) // 2
        return vals[mid] if len(vals) % 2 else \
            0.5 * (vals[mid - 1] + vals[mid])

    # -- layer 2: rollback -------------------------------------------------
    def rollback(self) -> Tuple[Any, Any, float]:
        """Restore the last verified snapshot. Returns ``(state, aux,
        lr_scale)``; raises :class:`TrainingDiverged` when the budget is
        spent or no process can restore (the capability is exchanged at
        ``begin_fit`` so every SPMD rank takes the same branch)."""
        can = self.restore_fn is not None if self._all_can_restore is None \
            else self._all_can_restore
        if not can or self.rollbacks >= self.config.rollback_budget:
            _diverged.inc()
            self._journal({"event": "diverged",
                           "rollbacks": self.rollbacks,
                           "budget": self.config.rollback_budget,
                           "restorable": bool(can)})
            raise TrainingDiverged(
                f"{self.name}: training diverged and the guard is out of "
                f"options (rollbacks {self.rollbacks}/"
                f"{self.config.rollback_budget}, "
                f"restore {'un' if not can else ''}available)")
        try:
            state, aux = self.restore_fn()
        except Exception as e:  # noqa: BLE001 — no snapshot ≡ no ladder
            _diverged.inc()
            self._journal({"event": "diverged", "restore_error": repr(e)})
            raise TrainingDiverged(
                f"{self.name}: rollback restore failed ({e!r})") from e
        self.rollbacks += 1
        _rollbacks.inc()
        lr_scale = self.config.lr_backoff if self.config.lr_backoff \
            and self.config.lr_backoff != 1.0 else 1.0
        self._window.clear()
        self._bad_seen = 0  # fresh device counters follow the restore
        self._journal({"event": "rollback", "n": self.rollbacks,
                       "lr_scale": lr_scale,
                       "restored_step": state.get("epoch")
                       if isinstance(state, dict) else None})
        logger.warning(
            "%s: rollback %d/%d restored last verified checkpoint "
            "(lr x%g on resume)", self.name, self.rollbacks,
            self.config.rollback_budget, lr_scale)
        return state, aux, lr_scale

    # -- layer 3: preemption ----------------------------------------------
    def _preempt_step(self, global_step: int) -> bool:
        """Advance the cross-host agreement; True once THIS boundary is
        the agreed checkpoint step."""
        pc, pid = _world()
        client = _kv_client() if pc > 1 else None
        if pc > 1 and client is not None:
            # job-global namespace (NOT per-fit): the KV store dies with
            # the coordinator, and a preempted job exits — stale keys
            # cannot leak into the relaunched attempt's fresh store
            p = "zoo/guard/preempt/"
            if self._preempt_flag.is_set() and not self._preempt_published:
                try:
                    client.key_value_set(f"{p}req", "1")
                except Exception:
                    pass  # a re-set from another rank races: fine
                self._preempt_published = True
            if not self._preempt_flag.is_set():
                # cheap poll: has any other rank requested?
                try:
                    client.blocking_key_value_get(f"{p}req", 1)
                    self._preempt_flag.set()
                except Exception:
                    return False
            if not self._preempt_acked:
                try:
                    client.key_value_set(f"{p}ack/{pid}",
                                         str(int(global_step)))
                except Exception:
                    pass
                self._preempt_acked = True
            if self._preempt_target is None:
                try:
                    if pid == 0:
                        acks = [int(client.blocking_key_value_get(
                            f"{p}ack/{q}", 60_000)) for q in range(pc)]
                        self._preempt_target = max(acks)
                        client.key_value_set(f"{p}target",
                                             str(self._preempt_target))
                    else:
                        self._preempt_target = int(
                            client.blocking_key_value_get(
                                f"{p}target", 60_000))
                except Exception as e:
                    logger.warning(
                        "preempt-step agreement failed (%s); falling "
                        "back to an uncoordinated local checkpoint", e)
                    self._preempt_target = int(global_step)
            return global_step >= self._preempt_target
        return self._preempt_flag.is_set()

    def preempt_checkpoint(self, save_cb: Optional[Callable[[], None]]
                           = None, step: int = 0):
        """Checkpoint once (rank 0, or whoever holds a ``save_fn``),
        publish completion over the KV store so no rank exits before the
        snapshot is committed, then raise :class:`Preempted`."""
        pc, pid = _world()
        saver = save_cb or self.save_fn
        saved = False
        if saver is not None:
            saver()
            saved = True
        elif pid == 0:
            logger.warning(
                "%s: preempted with no checkpoint callback configured — "
                "exiting without a fresh snapshot (resume falls back to "
                "the previous one)", self.name)
        if pc > 1:
            client = _kv_client()
            if client is not None:
                p = "zoo/guard/preempt/"
                try:
                    if pid == 0:
                        client.key_value_set(f"{p}done", "1")
                    else:
                        client.blocking_key_value_get(f"{p}done", 120_000)
                except Exception as e:
                    logger.warning("preempt done-barrier failed (%s); "
                                   "exiting anyway", e)
        if saved:
            self.preempt_checkpoints += 1
            _preempt_ckpts.inc()
        self._journal({"event": "preempt_checkpoint", "step": int(step),
                       "saved": saved, "rank": pid})
        # flight-recorder postmortem on the way out: the rc-75 exit is
        # deliberate, but the bundle (recent events + metrics + config)
        # is what explains the preemption window afterwards. Best
        # effort — a dump failure must never block the exit protocol.
        try:
            from zoo_tpu.obs.flight import dump_bundle, record_event
            record_event("preempt_exit", step=int(step), saved=saved,
                         rank=pid)
            dump_bundle("preempt-rc75")
        except Exception:  # noqa: BLE001
            pass
        logger.warning(
            "%s: preemption checkpoint at step %d complete; exiting "
            "with code %d (resume-don't-retry)", self.name, step,
            PREEMPT_EXIT_CODE)
        raise Preempted(step)

    # -- journal -----------------------------------------------------------
    def _journal(self, record: Dict):
        """Append one event to the quarantine/transition JSONL. Never
        raises — a journal failure must not take training down with it
        (numpy scalars from restored checkpoints coerce via default=)."""
        path = self.quarantine_path
        if not path:
            return
        record = {"ts": time.time(), "guard": self.name, **record}
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with self._lock, open(path, "a") as f:
                f.write(json.dumps(
                    record,
                    default=lambda o: o.item()
                    if hasattr(o, "item") else repr(o)) + "\n")
        except Exception as e:  # noqa: BLE001 — best-effort forensics
            logger.debug("guard journal write failed: %s", e)
