"""Horovod fabric shim (reference: ``orca/learn/horovod`` +
``horovod_ray_runner.py:81``).

On TPU every data-parallel fabric — Horovod's ring allreduce included —
collapses into XLA collectives over the ICI mesh (SURVEY §2.11), so
there is nothing to run Horovod *on*. The reference import path resolves
and points at the one fabric."""


class HorovodRayRunner:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "Horovod does not exist on TPU — data parallelism is XLA "
            "collectives over the mesh. Use orca.learn.pytorch / "
            "orca.learn.tf2 / orca.learn.keras Estimators; "
            "init_orca_context(mesh_axes={'data': -1}) IS the allreduce "
            "fabric")


def run(*args, **kwargs):
    raise NotImplementedError(
        "Horovod does not exist on TPU — data parallelism is XLA "
        "collectives over the mesh; see HorovodRayRunner's message")
