"""Versioned training checkpoints (orbax-backed), crash-safe.

Rebuild of the reference's checkpoint dir convention — time-stamped dir with
``model.N`` / ``optimMethod-<name>.N`` snapshots, resumed by
``load_orca_checkpoint(path, version)`` picking the latest N
(``Topology.scala:1245-1252``, ``orca/learn/tf/estimator.py:270``,
``pytorch/estimator.py:555``). Here a checkpoint is one step directory
holding the whole train state pytree (params + optimizer state).

Crash-safety contract (what ``run_elastic``'s scale-down resume assumes):
a worker may be ``kill -9``'d at ANY instant during :meth:`save` and
:meth:`restore` still returns the newest *verified* step.

* every save is staged into a dot-prefixed temp dir on the same
  filesystem, each file fsynced, then atomically renamed into place —
  readers never observe a half-written step directory;
* ``manifest.json`` records per-file size + sha256; :meth:`restore`
  verifies it, renames corrupt/incomplete steps to ``<step>.corrupt``
  (quarantine, kept for forensics) and falls back to the next-newest
  verified step;
* stale temp dirs left by killed savers are garbage-collected once their
  owning pid is gone.
"""

from __future__ import annotations

import logging
import os
import pickle
import re
import shutil
from typing import Any, List, Optional

import jax
import numpy as np

from zoo_tpu.obs.metrics import counter, histogram
from zoo_tpu.obs.tracing import span
from zoo_tpu.util.manifest import (
    MANIFEST,
    fsync_dir as _fsync_dir,
    prune_corrupt,
    prune_dirs,
    quarantine_dir,
    reap_stale_staging,
    sha256_file as _sha256,
    verify_manifest,
    walk_files as _walk_files,
    write_durable as _write_durable,
    write_manifest,
)
from zoo_tpu.util.resilience import env_int, fault_point

logger = logging.getLogger(__name__)

_save_seconds = histogram(
    "zoo_ckpt_save_seconds", "Checkpoint save wall time (stage + fsync + "
    "manifest + atomic rename)")
_restore_seconds = histogram(
    "zoo_ckpt_restore_seconds", "Checkpoint restore wall time (verify + load)")
_verify_seconds = histogram(
    "zoo_ckpt_verify_seconds", "Manifest verification wall time")
_quarantined = counter(
    "zoo_ckpt_quarantined_total",
    "Corrupt/incomplete checkpoint steps moved to <step>.corrupt")

_STEP_RE = re.compile(r"^(\d+)$")
_TMP_RE = re.compile(r"^\.tmp-(\d+)-(\d+)$")  # .tmp-<step>-<pid>
_STALE_RE = re.compile(r"^(\d+)\.stale-(\d+)$")  # <step>.stale-<pid>


class CheckpointCorruptError(RuntimeError):
    """An explicitly requested step failed manifest verification."""


def _ensure_host(tree):
    def to_host(a):
        if hasattr(a, "is_fully_addressable") and \
                not a.is_fully_addressable:
            # multi-process global array: this process only holds its
            # shards; np.asarray would raise. DP-replicated params have
            # a full copy in the first addressable shard.
            shard = a.addressable_shards[0]
            if shard.data.shape == a.shape:
                return np.asarray(shard.data)
            raise ValueError(
                "cannot checkpoint a cross-process SHARDED array from "
                "one process; gather it (e.g. "
                "multihost_utils.process_allgather) first")
        return np.asarray(a)

    return jax.tree_util.tree_map(to_host, tree)


def _apply_sharding(tree: Any, sharding: Any) -> Any:
    """Re-place a restored host pytree onto device(s) per ``sharding``:

    - a ``jax.sharding.Mesh`` — every array leaf is placed by the
      parameter plan (``zoo_tpu.parallel.plans``), scalars/metadata left
      alone. THE resharding-on-restore form: a checkpoint saved at world
      size N restores onto an M-device mesh bit-exactly (host bytes are
      layout-free; placement just scatters them differently);
    - a callable ``leaf -> Sharding`` — per-leaf control;
    - a pytree of Shardings matching ``tree`` — explicit placement.
    """
    if sharding is None:
        return tree
    from jax.sharding import Mesh, Sharding

    if isinstance(sharding, Mesh):
        from zoo_tpu.parallel.plans import named_leaf_sharding, _leaf_name

        def place(path, leaf):
            if not (hasattr(leaf, "ndim") and hasattr(leaf, "dtype")):
                return leaf  # epoch counters etc.: not array state
            return jax.device_put(leaf, named_leaf_sharding(
                sharding, _leaf_name(path), np.shape(leaf)))

        return jax.tree_util.tree_map_with_path(place, tree)
    if callable(sharding) and not isinstance(sharding, Sharding):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding(a)), tree)
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, s), tree, sharding)


class CheckpointManager:
    """Crash-safe orbax wrapper with a pickle fallback for exotic pytrees."""

    def __init__(self, directory: str, max_to_keep: Optional[int] = None,
                 keep: Optional[int] = None):
        """``keep`` (alias ``max_to_keep``; default ``$ZOO_CKPT_KEEP`` or
        5) is the retention bound: :meth:`gc` keeps the newest ``keep``
        committed steps AND at most ``keep`` quarantined
        ``<step>.corrupt`` dirs — without it both grow one directory per
        save/quarantine forever on a long-running trainer. The newest
        hash-VERIFIED step is never a GC victim, so the
        newest-verified fallback chain (docs/fault_tolerance.md)
        survives even when every younger step is corrupt."""
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if keep is None:
            keep = max_to_keep if max_to_keep is not None else \
                env_int("ZOO_CKPT_KEEP", 5)
        self.max_to_keep = int(keep)
        # steps this process already hash-verified: restore(None) followed
        # by restore_aux(None) — the elastic resume path — must not read
        # and sha256 a multi-GB snapshot twice
        self._verified_ok: set = set()
        try:
            import orbax.checkpoint as ocp
            self._ocp = ocp
            self._ckptr = ocp.StandardCheckpointer()
        except ImportError:  # pragma: no cover
            self._ocp = None
            self._ckptr = None

    # -- write ------------------------------------------------------------
    def save(self, step: int, state: Any, aux: Any = None):
        """``aux`` is an optional side pytree (e.g. optax optimizer state,
        whose NamedTuple structure orbax would flatten) stored pickled next
        to the main state — the reference writes ``optimMethod-<name>.N``
        beside ``model.N`` the same way.

        The step is staged under ``.tmp-<step>-<pid>`` (same filesystem),
        fsynced, manifested, then renamed into place in one atomic step —
        a crash at any point leaves either the previous verified state or
        the complete new one, never a torn directory.
        """
        with span("ckpt.save", step=int(step)), _save_seconds.time():
            self._save(step, state, aux)

    def _save(self, step: int, state: Any, aux: Any = None):
        final = os.path.join(self.directory, str(step))
        tmp = os.path.join(self.directory, f".tmp-{step}-{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        host_state = _ensure_host(state)
        fault_point("ckpt.pre_write", step=step, dir=tmp)
        saved = False
        # orbax's save runs a cross-process barrier; a single-rank save
        # (the estimator checkpoints from rank 0 only) would deadlock
        # every other rank's next collective — use the pickle path
        if self._ckptr is not None and jax.process_count() == 1:
            ocp_dir = os.path.join(tmp, "ocp")
            try:
                self._ckptr.save(ocp_dir, host_state, force=True)
                self._ckptr.wait_until_finished()
                saved = True
            except Exception as e:
                logger.warning(
                    "orbax save for step %d failed (%s: %s); falling "
                    "back to the pickle codec at %s", step,
                    type(e).__name__, e, os.path.join(tmp, "state.pkl"))
                shutil.rmtree(ocp_dir, ignore_errors=True)
        if not saved:
            _write_durable(
                os.path.join(tmp, "state.pkl"),
                pickle.dumps(host_state, protocol=pickle.HIGHEST_PROTOCOL))
        if aux is not None:
            _write_durable(
                os.path.join(tmp, "aux.pkl"),
                pickle.dumps(_ensure_host(aux),
                             protocol=pickle.HIGHEST_PROTOCOL))
        fault_point("ckpt.pre_manifest", step=step, dir=tmp)
        # orbax already fsyncs its own payload? not guaranteed —
        # write_manifest fsyncs everything it vouches for
        write_manifest(tmp, extra={"step": int(step)})
        fault_point("ckpt.pre_rename", step=step, dir=tmp)
        stale = None
        if os.path.isdir(final):
            # re-save of an existing step: move the old copy aside (not
            # delete!) so that at every instant either the old verified
            # step or the new one is in place — the stale copy is dropped
            # only AFTER the commit rename; a crash in between leaves a
            # .stale-* orphan that _gc sweeps, never a missing step
            stale = final + f".stale-{os.getpid()}"
            shutil.rmtree(stale, ignore_errors=True)
            os.rename(final, stale)
        os.rename(tmp, final)  # the atomic commit point
        if stale is not None:
            shutil.rmtree(stale, ignore_errors=True)
        self._verified_ok.discard(step)  # content changed: re-verify on read
        _fsync_dir(self.directory)
        fault_point("ckpt.post_rename", step=step, dir=final)
        self._gc()

    # -- read -------------------------------------------------------------
    def all_steps(self) -> List[int]:
        """Committed step numbers (temp ``.tmp-*`` and quarantined
        ``*.corrupt`` directories never match)."""
        steps = []
        if not os.path.isdir(self.directory):
            return steps
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_verified_step(self) -> Optional[int]:
        """Newest step that passes manifest verification; corrupt steps
        found on the way are quarantined."""
        for s in reversed(self.all_steps()):
            if self._verify_or_quarantine(s):
                return s
        return None

    def verify(self, step: int) -> bool:
        """Does ``step`` pass its manifest (sizes + checksums)? Steps
        written before the manifest era (no ``manifest.json``) are
        accepted when a payload file is present — they predate the
        atomic-rename protocol, so their presence implies a completed
        legacy save."""
        with _verify_seconds.time():
            return self._verify(step)

    def _verify(self, step: int) -> bool:
        # steps written before the manifest era predate the atomic-
        # rename protocol, so their mere presence implies a completed
        # legacy save (legacy_ok)
        return verify_manifest(os.path.join(self.directory, str(step)),
                               what=f"checkpoint step {step}",
                               legacy_ok=True)

    def _verify_or_quarantine(self, step: int) -> bool:
        if step in self._verified_ok and \
                os.path.isdir(os.path.join(self.directory, str(step))):
            return True
        if self.verify(step):
            self._verified_ok.add(step)
            return True
        self._verified_ok.discard(step)
        if quarantine_dir(os.path.join(self.directory, str(step)),
                          what=f"checkpoint step {step}") is not None:
            _quarantined.inc()
        return False

    def restore(self, step: Optional[int] = None, target: Any = None,
                sharding: Any = None) -> Any:
        """Load checkpoint ``step``. ``step=None`` picks the newest
        VERIFIED step — corrupt or torn steps (a saver killed mid-write)
        are quarantined to ``<step>.corrupt`` and skipped. An explicit
        ``step`` that fails verification raises
        :class:`CheckpointCorruptError` after quarantining it.

        ``sharding`` re-places the restored host pytree onto devices:
        pass the CURRENT mesh (placement per the parameter plan), a
        ``leaf -> Sharding`` callable, or a matching pytree of
        Shardings. Checkpoints are world-size-free host bytes, so a
        snapshot saved at world size N restores bit-exactly at world
        size M — the half of elastic resume (``run_elastic`` re-mesh)
        the save side cannot provide."""
        with span("ckpt.restore", step=step), _restore_seconds.time():
            return _apply_sharding(self._restore(step, target), sharding)

    def _restore(self, step: Optional[int] = None, target: Any = None) -> Any:
        if step is not None:
            if not os.path.isdir(os.path.join(self.directory, str(step))):
                raise FileNotFoundError(
                    f"no checkpoint step {step} under {self.directory}")
            if not self._verify_or_quarantine(step):
                raise CheckpointCorruptError(
                    f"checkpoint step {step} under {self.directory} is "
                    "corrupt or incomplete (quarantined to "
                    f"{step}.corrupt)")
            return self._load(step, target)
        for s in reversed(self.all_steps()):
            if self._verify_or_quarantine(s):
                return self._load(s, target)
        raise FileNotFoundError(
            f"no verified checkpoints under {self.directory}")

    def _load(self, step: int, target: Any = None) -> Any:
        path = os.path.join(self.directory, str(step))
        pkl = os.path.join(path, "state.pkl")
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                return pickle.load(f)
        if self._ckptr is None:
            raise FileNotFoundError(path)
        ocp_dir = os.path.join(path, "ocp")
        src = ocp_dir if os.path.isdir(ocp_dir) else path  # legacy layout
        if target is not None:
            return self._ckptr.restore(src, target=_ensure_host(target))
        return self._ckptr.restore(src)

    def restore_with_aux(self, step: Optional[int] = None,
                         target: Any = None, sharding: Any = None,
                         aux_sharding: Any = None):
        """``(step, state, aux)`` from one verified snapshot — the
        resume/rollback primitive: params and optimizer state are
        guaranteed to come from the SAME step (``restore`` followed by a
        separate ``restore_aux(None)`` could straddle a concurrent save).
        ``step=None`` picks the newest verified step; raises
        ``FileNotFoundError`` when none exists.

        ``sharding`` places the state (see :meth:`restore`);
        ``aux_sharding`` places the aux pytree — when it is a Mesh the
        same plan applies, which matches how fit initializes optimizer
        moments (zeros_like of the placed params)."""
        if step is None:
            step = self.latest_verified_step()
            if step is None:
                raise FileNotFoundError(
                    f"no verified checkpoints under {self.directory}")
        return (step, self.restore(step, target, sharding),
                self.restore_aux(step, aux_sharding))

    def restore_aux(self, step: Optional[int] = None,
                    sharding: Any = None) -> Any:
        """Load the side pytree written with ``save(..., aux=...)``;
        None if the step has none. ``step=None`` follows the same
        newest-VERIFIED-step rule as :meth:`restore`, so params and
        optimizer state always come from the same snapshot.
        ``sharding`` as in :meth:`restore`."""
        if step is None:
            step = self.latest_verified_step()
        if step is None:
            return None
        path = os.path.join(self.directory, str(step), "aux.pkl")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return _apply_sharding(pickle.load(f), sharding)

    # -- housekeeping ------------------------------------------------------
    @property
    def keep(self) -> int:
        """Retention bound (``keep=`` / ``max_to_keep=`` ctor alias)."""
        return self.max_to_keep

    def gc(self):
        """Bounded disk hygiene (also runs after every :meth:`save`):
        keep the newest ``keep`` committed steps — but NEVER the newest
        step this process has hash-verified, so the restore fallback
        chain survives a run whose youngest steps are all torn — age out
        ``<step>.corrupt`` quarantine dirs past the same bound, and
        reap staging/stale dirs whose owning pid is gone."""
        self._gc()

    def _gc(self):
        steps = self.all_steps()
        # the newest step KNOWN verified is the restore fallback anchor:
        # deleting it while every younger step is corrupt would leave
        # restore(None) with nothing — protect it from retention
        verified = [s for s in steps if s in self._verified_ok]
        protect = {str(verified[-1])} if verified else set()
        prune_dirs(self.directory, [str(s) for s in steps],
                   self.max_to_keep, protect=protect)
        prune_corrupt(self.directory, self.max_to_keep)
        reap_stale_staging(self.directory, _TMP_RE, _STALE_RE)
