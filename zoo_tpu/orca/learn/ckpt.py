"""Versioned training checkpoints (orbax-backed).

Rebuild of the reference's checkpoint dir convention — time-stamped dir with
``model.N`` / ``optimMethod-<name>.N`` snapshots, resumed by
``load_orca_checkpoint(path, version)`` picking the latest N
(``Topology.scala:1245-1252``, ``orca/learn/tf/estimator.py:270``,
``pytorch/estimator.py:555``). Here a checkpoint is one orbax step directory
holding the whole train state pytree (params + optimizer state), written
asynchronously off the training loop.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Any, Optional

import jax
import numpy as np

_STEP_RE = re.compile(r"^(\d+)$")


def _ensure_host(tree):
    def to_host(a):
        if hasattr(a, "is_fully_addressable") and \
                not a.is_fully_addressable:
            # multi-process global array: this process only holds its
            # shards; np.asarray would raise. DP-replicated params have
            # a full copy in the first addressable shard.
            shard = a.addressable_shards[0]
            if shard.data.shape == a.shape:
                return np.asarray(shard.data)
            raise ValueError(
                "cannot checkpoint a cross-process SHARDED array from "
                "one process; gather it (e.g. "
                "multihost_utils.process_allgather) first")
        return np.asarray(a)

    return jax.tree_util.tree_map(to_host, tree)


class CheckpointManager:
    """Thin orbax wrapper with a pickle fallback for exotic pytrees."""

    def __init__(self, directory: str, max_to_keep: int = 5):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        try:
            import orbax.checkpoint as ocp
            self._ocp = ocp
            self._ckptr = ocp.StandardCheckpointer()
        except ImportError:  # pragma: no cover
            self._ocp = None
            self._ckptr = None

    # -- write ------------------------------------------------------------
    def save(self, step: int, state: Any, aux: Any = None):
        """``aux`` is an optional side pytree (e.g. optax optimizer state,
        whose NamedTuple structure orbax would flatten) stored pickled next
        to the main state — the reference writes ``optimMethod-<name>.N``
        beside ``model.N`` the same way."""
        path = os.path.join(self.directory, str(step))
        host_state = _ensure_host(state)
        saved = False
        # orbax's save runs a cross-process barrier; a single-rank save
        # (the estimator checkpoints from rank 0 only) would deadlock
        # every other rank's next collective — use the pickle path
        if self._ckptr is not None and jax.process_count() == 1:
            try:
                self._ckptr.save(path, host_state, force=True)
                self._ckptr.wait_until_finished()
                saved = True
            except Exception:
                pass
        if not saved:
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "state.pkl"), "wb") as f:
                pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        if aux is not None:
            with open(os.path.join(path, "aux.pkl"), "wb") as f:
                pickle.dump(_ensure_host(aux), f,
                            protocol=pickle.HIGHEST_PROTOCOL)
        self._gc()

    # -- read -------------------------------------------------------------
    def all_steps(self):
        steps = []
        if not os.path.isdir(self.directory):
            return steps
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None, target: Any = None) -> Any:
        """Load checkpoint ``step`` (None → latest; reference
        ``find_latest_checkpoint`` filename-convention scan)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        path = os.path.join(self.directory, str(step))
        pkl = os.path.join(path, "state.pkl")
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                return pickle.load(f)
        if self._ckptr is None:
            raise FileNotFoundError(path)
        if target is not None:
            return self._ckptr.restore(path, target=_ensure_host(target))
        return self._ckptr.restore(path)

    def restore_aux(self, step: Optional[int] = None) -> Any:
        """Load the side pytree written with ``save(..., aux=...)``;
        None if the step has none."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        path = os.path.join(self.directory, str(step), "aux.pkl")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return pickle.load(f)

    def _gc(self):
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            import shutil
            shutil.rmtree(os.path.join(self.directory, str(victim)),
                          ignore_errors=True)
