"""Orca Estimator over the Keras-facade models.

Rebuild of the Orca Estimator family (reference: base interfaces
``orca/learn/base_estimator.py`` / ``spark_estimator.py``; the BigDL-backed
keras path ``orca/learn/bigdl/estimator.py:72``): uniform
``fit/predict/evaluate/get_model/save/load`` over XShards / pandas / numpy
inputs, with orca-style checkpointing and train-summary read-back.

Where the reference funnels every fit into the Scala
``InternalDistriOptimizer`` (2 Spark jobs + PS allreduce per iteration,
``Topology.scala:1160``), this estimator drives the jitted pjit step of
:class:`zoo_tpu.pipeline.api.keras.engine.topology.KerasNet` directly — the
mesh from ``init_orca_context`` supplies the data-parallel sharding.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from zoo_tpu.orca.learn.ckpt import CheckpointManager
from zoo_tpu.orca.learn.guard import TrainingDiverged, TrainingGuard
from zoo_tpu.orca.learn.trigger import EveryEpoch, Trigger


class Estimator:
    """Factory namespace, mirroring ``Estimator.from_*`` in the reference."""

    @staticmethod
    def from_keras(model, model_dir: Optional[str] = None,
                   max_ckpt_to_keep: int = 5,
                   guard=None) -> "KerasEstimator":
        """Wrap a compiled Keras-facade model (reference:
        ``orca/learn/bigdl/estimator.py:72`` ``Estimator.from_bigdl``).

        ``guard``: a :class:`zoo_tpu.orca.learn.guard.TrainingGuard` (or
        False to disable). Default: one configured from ``ZOO_GUARD_*``
        env — the in-step numeric-health guard, divergence rollback and
        preemption-safe checkpointing described in
        docs/fault_tolerance.md."""
        return KerasEstimator(model, model_dir=model_dir,
                              max_ckpt_to_keep=max_ckpt_to_keep,
                              guard=guard)

    @staticmethod
    def from_bigdl(*, model, loss=None, optimizer=None, metrics=None,
                   feature_preprocessing=None, label_preprocessing=None,
                   model_dir: Optional[str] = None) -> "KerasEstimator":
        """reference ``Estimator.from_bigdl(model=..., loss=...,
        optimizer=...)`` — "BigDL models" here ARE the keras-facade
        models, so this compiles the given pieces and wraps the result
        exactly like ``from_keras``."""
        if loss is not None or optimizer is not None \
                or metrics is not None:
            model.compile(optimizer=optimizer or "adam",
                          loss=loss or "mse", metrics=metrics)
        elif model.loss_fn is None:
            raise ValueError("from_bigdl: pass loss=/optimizer= or a "
                             "compiled model")
        return KerasEstimator(model, model_dir=model_dir)


class KerasEstimator:
    def __init__(self, model, model_dir: Optional[str] = None,
                 max_ckpt_to_keep: int = 5, guard=None):
        self.model = model
        self.model_dir = model_dir
        self._epoch = 0
        self._ckpt = None
        if model_dir:
            self._ckpt = CheckpointManager(
                os.path.join(model_dir, "ckpts"),
                max_to_keep=max_ckpt_to_keep)
            self.model.set_tensorboard(model_dir, "summaries")
        # training guardian (docs/fault_tolerance.md): in-step NaN/inf
        # skip, divergence rollback from the last verified checkpoint,
        # preemption-safe checkpoint-and-exit. On by default; pass
        # guard=False or set ZOO_GUARD=0 to run unguarded.
        if guard is False:
            self._guard = None
        elif guard is not None:
            self._guard = guard
        else:
            self._guard = TrainingGuard.from_env()
        self._bind_guard()

    def _bind_guard(self):
        """(Re)wire the guard's checkpoint callbacks to the current
        CheckpointManager; called again by estimators that build their
        manager lazily (pytorch)."""
        if self._guard is None:
            return
        if self._ckpt is not None:
            self._guard.bind(
                save_fn=self._save_checkpoint,
                restore_fn=lambda: self._ckpt.restore_with_aux(None)[1:],
                quarantine_path=os.path.join(
                    self.model_dir, "guard", "quarantine.jsonl")
                if self.model_dir else None)
        if self.model is not None:
            self.model.set_guard(self._guard)

    # -- training ---------------------------------------------------------
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols: Optional[Sequence[str]] = None,
            label_cols: Optional[Sequence[str]] = None,
            validation_data=None,
            checkpoint_trigger: Optional[Trigger] = None,
            shuffle: bool = True,
            max_failure_retries: int = 5,
            retry_time_interval: float = 120.0) -> Dict[str, List[float]]:
        """reference: ``spark_estimator.Estimator.fit`` signature (data,
        epochs, batch_size, feature_cols, label_cols, validation_data,
        checkpoint_trigger).

        Elastic training (reference: ``Topology.scala:1255-1337``, SURVEY
        §5.3): when a ``model_dir`` checkpoint manager is configured, any
        exception inside an epoch restores the latest snapshot (params +
        optimizer state) and retries, bounded by ``max_failure_retries``
        failures within a ``retry_time_interval``-second sliding window
        (the reference's ``bigdl.failure.retryTimes`` /
        ``retryTimeInterval`` sysprops, defaults 5 / 120s). Without a
        checkpoint manager there is nothing to restore, so failures
        propagate immediately."""
        if checkpoint_trigger is None and self._ckpt is not None:
            checkpoint_trigger = EveryEpoch()
        if self._ckpt is not None and self._ckpt.latest_step() is None \
                and self.model.params is not None:
            # snapshot the starting point so a first-epoch failure has
            # somewhere to restore to
            self._save_checkpoint()
        if self._guard is not None:
            # the preemption signal (SIGTERM / $ZOO_PREEMPT) is owned for
            # the whole fit, including the gaps between epochs; the guard
            # acts on it at the next step boundary
            self._guard.install_signal_handler()
        try:
            return self._fit_epochs(
                data, epochs, batch_size, feature_cols, label_cols,
                validation_data, checkpoint_trigger, shuffle,
                max_failure_retries, retry_time_interval)
        finally:
            if self._guard is not None:
                self._guard.uninstall_signal_handler()

    def _fit_epochs(self, data, epochs, batch_size, feature_cols,
                    label_cols, validation_data, checkpoint_trigger,
                    shuffle, max_failure_retries, retry_time_interval):
        import logging
        import time as _time

        history: Dict[str, List[float]] = {}
        retries, no_progress, last_failure = 0, 0, 0.0
        # train until the epoch counter reaches target — a rollback lowers
        # the counter, so lost epochs are retrained (reference endWhen)
        start_epoch = self._epoch
        target = self._epoch + epochs
        while self._epoch < target:
            try:
                h = self.model.fit(
                    data, batch_size=batch_size, nb_epoch=1,
                    validation_data=validation_data,
                    feature_cols=feature_cols, label_cols=label_cols,
                    shuffle=shuffle, seed=self._epoch, verbose=0)
            except TrainingDiverged:
                # the guard already exhausted its in-fit rollback budget;
                # retrying from the same snapshot would diverge again
                raise
            except Exception as e:  # noqa: BLE001 — the retry perimeter
                now = _time.monotonic()
                if now - last_failure > retry_time_interval:
                    retries = 0  # sliding window: old failures expire
                retries += 1
                no_progress += 1
                last_failure = now
                if (self._ckpt is None
                        or self._ckpt.latest_step() is None
                        or retries > max_failure_retries
                        # a deterministic failure slower than the window
                        # must not retry forever: hard-cap consecutive
                        # rollbacks with no completed epoch in between
                        or no_progress > 2 * max_failure_retries):
                    raise
                logging.getLogger(__name__).warning(
                    "training failed (%s: %s); retry %d/%d from latest "
                    "checkpoint", type(e).__name__, e, retries,
                    max_failure_retries)
                epoch_before = self._epoch
                self._restore_latest()
                if self._epoch > epoch_before:
                    # the newest checkpoint is from a DIFFERENT run (stale
                    # model_dir): restoring it would silently skip training
                    raise RuntimeError(
                        f"latest checkpoint (epoch {self._epoch}) is ahead "
                        f"of this run (epoch {epoch_before}) — model_dir "
                        "holds checkpoints from a previous run; use "
                        "load_orca_checkpoint() to resume or point "
                        "model_dir at a fresh directory") from e
                # drop history entries for epochs the rollback undid, so
                # retrained epochs don't append duplicates
                keep = max(0, self._epoch - start_epoch)
                for k in history:
                    history[k] = history[k][:keep]
                continue
            no_progress = 0
            self._epoch += 1
            for k, v in h.items():
                history.setdefault(k, []).extend(v)
            if (self._ckpt is not None and checkpoint_trigger is not None
                    and checkpoint_trigger.fire_on_epoch(self._epoch)):
                self._save_checkpoint()
        return history

    def _save_checkpoint(self):
        state = {"params": self.model.params, "epoch": self._epoch}
        self._ckpt.save(self._epoch, state, aux=self.model._opt_state)

    @staticmethod
    def _restore_mesh():
        """The live mesh when one is active with >1 device — the
        resharding target for restores, so a checkpoint saved at any
        world size lands pre-placed for THIS run's layout (the
        run_elastic re-mesh path; docs/multichip.md)."""
        from zoo_tpu.common.context import get_runtime_context
        ctx = get_runtime_context(required=False)
        mesh = ctx.mesh if ctx is not None else None
        return mesh if mesh is not None and mesh.size > 1 else None

    def _restore_latest(self):
        """Reload the newest snapshot: params, optimizer state, epoch
        counter — the reference's retry loop reloads ``model.N`` +
        ``optimMethod-*.N`` the same way. ``restore_with_aux`` pins both
        pytrees to ONE verified step."""
        mesh = self._restore_mesh()
        _, state, aux = self._ckpt.restore_with_aux(
            None, sharding=mesh, aux_sharding=mesh)
        self.model.params = state["params"]
        self.model._opt_state = aux
        self._epoch = int(state.get("epoch", 0))

    def load_orca_checkpoint(self, path: Optional[str] = None,
                             version: Optional[int] = None):
        """Resume from a checkpoint dir (reference:
        ``orca/learn/tf/estimator.py:270`` — version None picks latest)."""
        mgr = self._ckpt if path is None else CheckpointManager(
            os.path.join(path, "ckpts") if os.path.isdir(
                os.path.join(path, "ckpts")) else path)
        if mgr is None:
            raise ValueError("no model_dir configured and no path given")
        mesh = self._restore_mesh()
        state = mgr.restore(version, sharding=mesh)
        self.model.params = state["params"]
        # optimizer state (Adam moments etc.) resumes too — the reference
        # reloads optimMethod-<name>.N alongside model.N; both pytrees
        # land resharded for the CURRENT mesh, so a world-size change
        # between save and resume (elastic scale-down) is transparent
        self.model._opt_state = mgr.restore_aux(version, sharding=mesh)
        self._epoch = int(state.get("epoch", 0))
        return self

    # -- inference / eval --------------------------------------------------
    def predict(self, data, batch_size: int = 256,
                feature_cols: Optional[Sequence[str]] = None) -> np.ndarray:
        return self.model.predict(data, batch_size=batch_size,
                                  feature_cols=feature_cols)

    def evaluate(self, data, batch_size: int = 32,
                 feature_cols: Optional[Sequence[str]] = None,
                 label_cols: Optional[Sequence[str]] = None
                 ) -> Dict[str, float]:
        return self.model.evaluate(data, batch_size=batch_size,
                                   feature_cols=feature_cols,
                                   label_cols=label_cols)

    # -- persistence / summaries ------------------------------------------
    def get_model(self):
        return self.model

    def save(self, model_path: str):
        self.model.save_weights(model_path)
        return model_path

    def load(self, model_path: str):
        self.model.load_weights(model_path)
        return self

    def set_tensorboard(self, log_dir: str, app_name: str):
        self.model.set_tensorboard(log_dir, app_name)

    def set_profile(self, trace_dir=None, trace_epochs: int = 1):
        """Per-phase step timers + optional XLA trace (SURVEY §5.1)."""
        return self.model.set_profile(trace_dir, trace_epochs)

    def clear_profile(self):
        self.model.clear_profile()

    def get_profile_stats(self):
        return self.model.get_profile_stats()

    def get_train_summary(self, tag: str = "Loss"):
        return self.model.get_train_summary(tag)

    def get_validation_summary(self, tag: str):
        return self.model.get_validation_summary(tag)

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float):
        """reference: ``spark_estimator.set_constant_gradient_clipping`` →
        Scala ``Estimator.scala`` constant clipping."""
        self.model.set_constant_gradient_clipping(min_value, max_value)

    def set_l2_norm_gradient_clipping(self, clip_norm: float):
        self.model.set_gradient_clipping_by_l2_norm(clip_norm)

    def clear_gradient_clipping(self):
        self.model.clear_gradient_clipping()

    def shutdown(self):
        pass  # no actors/JVM to tear down
