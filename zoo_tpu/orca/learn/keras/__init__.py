from zoo_tpu.orca.learn.keras.estimator import Estimator, KerasEstimator

__all__ = ["Estimator", "KerasEstimator"]
