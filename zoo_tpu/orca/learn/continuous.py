"""Continuous training → shadow-eval → promotion: the online-learning
loop that closes the paper's Chronos + Cluster Serving story
(docs/model_lifecycle.md).

The reference platform retrains on streaming data and pushes fresh
models at a live Flink/Redis serving job; what made that safe in
practice was never publishing a model straight to production. This
module is that discipline as code:

* :class:`PromotionGate` — a candidate version serves SHADOW traffic
  first: a sample of live requests is mirrored to the canary, and its
  error rate, latency, and (when ground truth is available) loss are
  compared against the incumbent over a configurable window. Only a
  candidate that holds up moves the ``prod`` alias.
* :class:`ContinuousTrainingLoop` — one turn of the crank: retrain on
  the latest streaming window (a diverging run — the TrainingGuard's
  :class:`~zoo_tpu.orca.learn.guard.TrainingDiverged` — **demotes the
  candidate instead of publishing it**), publish the artifact as an
  immutable registry version, stage it on the ``canary`` alias, run
  the gate, and on a PASS move ``prod`` + drive
  :meth:`~zoo_tpu.serving.ha.ReplicaGroup.rolling_update` so the live
  group hot-swaps one replica at a time with auto-rollback.

Importable without jax — the trainer side (``train_fn``) is where jax
lives, injected by the caller; :func:`chronos_train_fn` builds the
Chronos-forecaster flavor of it.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from zoo_tpu.obs.metrics import counter, gauge
from zoo_tpu.util.resilience import env_float, env_int, fault_point

logger = logging.getLogger(__name__)

__all__ = ["PromotionGate", "GateDecision", "ContinuousTrainingLoop",
           "chronos_train_fn"]

_promotions = counter(
    "zoo_promotion_total",
    "Shadow-eval promotion decisions, by outcome (promoted / rejected "
    "= the canary regressed and the prod alias did not move / demoted "
    "= training itself diverged and nothing was published)",
    labels=("outcome",))
_gate_error_rate = gauge(
    "zoo_promotion_canary_error_rate",
    "Canary error rate over the last completed shadow-eval window")
_gate_latency_ratio = gauge(
    "zoo_promotion_canary_latency_ratio",
    "Canary p50 latency / incumbent p50 latency over the last window")
_gate_loss_ratio = gauge(
    "zoo_promotion_canary_loss_ratio",
    "Canary loss / incumbent loss over the last window (ground-truth "
    "samples only; 0 when none were seen)")


class GateDecision:
    """Outcome of one shadow-eval window."""

    def __init__(self, promoted: bool, reason: str, stats: Dict):
        self.promoted = promoted
        self.reason = reason
        self.stats = stats

    def __repr__(self):
        verdict = "PROMOTE" if self.promoted else "REJECT"
        return f"GateDecision({verdict}: {self.reason})"


class PromotionGate:
    """Shadow-eval gate between a canary version and the incumbent.

    ``incumbent`` / ``canary`` are ``x -> prediction`` callables —
    typically ``HAServingClient.predict`` against the live group and a
    version-pinned (or dedicated canary replica) client. Live traffic
    flows through :meth:`offer`, which always answers from the
    INCUMBENT (the caller's users never see the canary) and mirrors a
    ``sample`` fraction to the canary, recording both sides' latency,
    errors, and — when the caller supplies ground truth — loss. Once
    ``window`` mirrored samples accumulated, :meth:`decision` compares:

    * canary error rate > ``max_error_rate``  → reject
    * canary p50 latency > ``max_latency_ratio`` × incumbent p50 → reject
    * canary loss > ``max_loss_ratio`` × incumbent loss (+ epsilon)
      → reject
    * otherwise → promote.

    Knob defaults come from the ``ZOO_GATE_*`` env
    (docs/model_lifecycle.md). The canary call sits behind
    ``fault_point("serving.canary")`` so chaos tests can inject a
    regressed canary without a genuinely bad model."""

    def __init__(self, incumbent: Callable, canary: Callable, *,
                 candidate: str,
                 registry=None, alias: str = "prod",
                 canary_alias: str = "canary",
                 sample: Optional[float] = None,
                 window: Optional[int] = None,
                 max_error_rate: Optional[float] = None,
                 max_latency_ratio: Optional[float] = None,
                 max_loss_ratio: Optional[float] = None,
                 loss_fn: Optional[Callable] = None,
                 rng: Optional[np.random.RandomState] = None,
                 slo_veto: bool = True):
        self._incumbent = incumbent
        self._canary = canary
        self.candidate = candidate
        # SLO veto (docs/observability.md): when the in-process SLO
        # watchdog reports an active burn-rate breach at decision
        # time, the gate refuses to promote — never move the prod
        # alias while the serving fleet is already missing its
        # objectives. No watchdog running = no veto.
        self.slo_veto = bool(slo_veto)
        self.registry = registry
        self.alias = alias
        self.canary_alias = canary_alias
        self.sample = sample if sample is not None else \
            env_float("ZOO_GATE_SAMPLE", 0.25)
        self.window = window if window is not None else \
            env_int("ZOO_GATE_WINDOW", 32)
        self.max_error_rate = max_error_rate if max_error_rate \
            is not None else env_float("ZOO_GATE_MAX_ERROR_RATE", 0.02)
        self.max_latency_ratio = max_latency_ratio \
            if max_latency_ratio is not None \
            else env_float("ZOO_GATE_MAX_LATENCY_RATIO", 3.0)
        self.max_loss_ratio = max_loss_ratio if max_loss_ratio \
            is not None else env_float("ZOO_GATE_MAX_LOSS_RATIO", 1.2)
        self._loss = loss_fn or (
            lambda y_true, y_pred: float(np.mean(
                (np.asarray(y_pred, np.float64) -
                 np.asarray(y_true, np.float64)) ** 2)))
        self._rng = rng or np.random.RandomState()
        self._mirrored = 0
        self._canary_errors = 0
        self._inc_lat: List[float] = []
        self._can_lat: List[float] = []
        self._inc_loss: List[float] = []
        self._can_loss: List[float] = []

    # -- traffic -----------------------------------------------------------
    def offer(self, x, y_true=None):
        """One live request: answered by the incumbent (errors
        propagate to the caller — the gate never changes what users
        see), mirrored to the canary with probability ``sample``."""
        t0 = time.perf_counter()
        result = self._incumbent(x)  # incumbent errors are the
        #                              caller's problem, not the gate's
        inc_dt = time.perf_counter() - t0
        if self._rng.random_sample() >= self.sample:
            return result
        self._mirrored += 1
        self._inc_lat.append(inc_dt)
        if y_true is not None:
            self._inc_loss.append(self._loss(y_true, result))
        t1 = time.perf_counter()
        try:
            # the chaos seam: fault-injected canary failures measure
            # the gate's rollback path without a genuinely bad model
            fault_point("serving.canary", candidate=self.candidate)
            shadow = self._canary(x)
        except Exception as e:  # noqa: BLE001 — a canary failure is
            # DATA (it counts against promotion), never user-visible
            self._canary_errors += 1
            logger.debug("canary mirror failed: %r", e)
            return result
        self._can_lat.append(time.perf_counter() - t1)
        if y_true is not None:
            self._can_loss.append(self._loss(y_true, shadow))
        return result

    def ready(self) -> bool:
        return self._mirrored >= self.window

    # -- verdict -----------------------------------------------------------
    def stats(self) -> Dict:
        p50 = lambda xs: float(np.percentile(xs, 50)) if xs else 0.0  # noqa: E731
        inc_p50, can_p50 = p50(self._inc_lat), p50(self._can_lat)
        inc_loss = float(np.mean(self._inc_loss)) if self._inc_loss \
            else None
        can_loss = float(np.mean(self._can_loss)) if self._can_loss \
            else None
        return {
            "mirrored": self._mirrored,
            "canary_errors": self._canary_errors,
            "canary_error_rate": self._canary_errors /
            max(1, self._mirrored),
            "incumbent_p50_s": inc_p50,
            "canary_p50_s": can_p50,
            "latency_ratio": (can_p50 / inc_p50) if inc_p50 > 0 else 1.0,
            "incumbent_loss": inc_loss,
            "canary_loss": can_loss,
        }

    def decision(self) -> GateDecision:
        s = self.stats()
        _gate_error_rate.set(s["canary_error_rate"])
        _gate_latency_ratio.set(s["latency_ratio"])
        if s["mirrored"] < self.window:
            return GateDecision(False, "window not filled "
                                f"({s['mirrored']}/{self.window})", s)
        if self.slo_veto:
            try:
                from zoo_tpu.obs.slo import last_status
                slo = last_status()
            except Exception:  # noqa: BLE001 — no watchdog, no veto
                slo = None
            if slo is not None and not slo.get("ok", True):
                s["slo"] = slo
                return GateDecision(
                    False, "SLO watchdog reports an active breach "
                    f"({', '.join(slo.get('breaches', []))}); "
                    "refusing to promote into a burning fleet", s)
        if s["canary_error_rate"] > self.max_error_rate:
            return GateDecision(
                False, f"canary error rate {s['canary_error_rate']:.1%} "
                f"> bound {self.max_error_rate:.1%}", s)
        if s["latency_ratio"] > self.max_latency_ratio:
            return GateDecision(
                False, f"canary p50 {s['canary_p50_s'] * 1e3:.1f}ms is "
                f"{s['latency_ratio']:.2f}x the incumbent "
                f"(bound {self.max_latency_ratio:.2f}x)", s)
        if s["incumbent_loss"] is not None and \
                s["canary_loss"] is not None:
            bound = self.max_loss_ratio * s["incumbent_loss"] + 1e-9
            _gate_loss_ratio.set(
                s["canary_loss"] / max(s["incumbent_loss"], 1e-12))
            if s["canary_loss"] > bound:
                return GateDecision(
                    False, f"canary loss {s['canary_loss']:.5f} > "
                    f"{self.max_loss_ratio:.2f}x incumbent "
                    f"{s['incumbent_loss']:.5f}", s)
        return GateDecision(True, "canary within bounds on error rate, "
                            "latency and loss", s)

    def run(self, traffic, promote: bool = True) -> GateDecision:
        """Drive ``traffic`` (an iterable of ``x`` or ``(x, y_true)``)
        through :meth:`offer` until the window fills, then decide. With
        ``promote=True`` and a registry, a PASS atomically moves the
        ``prod`` alias to the candidate — the only path that ever moves
        it — and a FAIL drops the ``canary`` alias (the candidate
        version stays in the registry for forensics, unaliased)."""
        for item in traffic:
            if isinstance(item, tuple):
                self.offer(*item)
            else:
                self.offer(item)
            if self.ready():
                break
        verdict = self.decision()
        _promotions.labels(
            outcome="promoted" if verdict.promoted else "rejected").inc()
        if self.registry is not None and promote:
            if verdict.promoted:
                self.registry.set_alias(self.alias, self.candidate)
                logger.info("promotion gate PASSED: %s -> %s (%s)",
                            self.alias, self.candidate, verdict.reason)
            else:
                if self.registry.alias_version(self.canary_alias) == \
                        self.candidate:
                    self.registry.drop_alias(self.canary_alias)
                logger.warning("promotion gate REJECTED %s: %s",
                               self.candidate, verdict.reason)
        return verdict


class ContinuousTrainingLoop:
    """One crank of the online-learning lifecycle per :meth:`step`:

    retrain → publish → canary → shadow-eval → promote → rolling swap,
    with the two failure exits the paper's always-on serving story
    needs: a DIVERGED retrain (the TrainingGuard escalated past its
    rollback budget) demotes the candidate before anything is
    published, and a REJECTED shadow-eval leaves ``prod`` untouched.

    ``train_fn(window) -> artifact`` runs the actual training and
    returns either a filesystem path (model file / SavedModel dir,
    published as payload) or a model spec string (published as a
    ``MODEL`` pointer — how jax-free tests exercise the loop).
    ``gate_factory(candidate) -> PromotionGate`` builds the gate once
    the candidate is staged on the canary alias (the caller decides
    where canary traffic is served — a pinned A/B slice of the live
    group or a dedicated canary replica)."""

    def __init__(self, train_fn: Callable, registry, *,
                 group=None,
                 gate_factory: Optional[Callable] = None,
                 alias: str = "prod", canary_alias: str = "canary"):
        self.train_fn = train_fn
        self.registry = registry
        self.group = group
        self.gate_factory = gate_factory
        self.alias = alias
        self.canary_alias = canary_alias

    def step(self, window, traffic=None) -> Dict:
        """Returns ``{"outcome": "promoted" | "rejected" | "demoted" |
        "rolled_back", "version": ..., ...}``."""
        from zoo_tpu.orca.learn.guard import TrainingDiverged
        t0 = time.perf_counter()
        try:
            artifact = self.train_fn(window)
        except TrainingDiverged as e:
            # the guard burned its rollback budget: this window's data
            # produced a diverging model — publish NOTHING; prod keeps
            # serving the incumbent
            _promotions.labels(outcome="demoted").inc()
            logger.warning("continuous step: training diverged, "
                           "candidate demoted before publish: %s", e)
            return {"outcome": "demoted", "version": None,
                    "error": str(e)}
        if isinstance(artifact, str) and os.path.exists(artifact):
            version = self.registry.publish(artifact,
                                            alias=self.canary_alias)
        else:
            version = self.registry.publish(spec=str(artifact),
                                            alias=self.canary_alias)
        out = {"version": version,
               "train_seconds": round(time.perf_counter() - t0, 3)}
        if self.gate_factory is None:
            # no gate configured: direct promotion (a dev/backfill
            # loop); production wires a gate
            self.registry.set_alias(self.alias, version)
            out["outcome"] = "promoted"
        else:
            gate = self.gate_factory(version)
            verdict = gate.run(traffic or ())
            out["gate"] = verdict.stats
            out["reason"] = verdict.reason
            if not verdict.promoted:
                out["outcome"] = "rejected"
                return out
            out["outcome"] = "promoted"
        # the alias MUST point at the promoted version before any
        # replica swaps (a gate built without registry= skips its own
        # alias move): a supervisor respawn mid-rolling-update
        # re-resolves the alias at boot, and a stale alias would bring
        # it up on the old version — a silently mixed group
        if self.registry.alias_version(self.alias) != version:
            self.registry.set_alias(self.alias, version)
        if self.group is not None:
            from zoo_tpu.serving.ha import RollingUpdateError
            try:
                out["rolling"] = self.group.rolling_update(version)
            except RollingUpdateError as e:
                # the gate passed but a live replica failed the swap —
                # rolling_update already returned the group AND the
                # alias to the incumbent
                out["outcome"] = "rolled_back"
                out["error"] = str(e)
        return out


def chronos_train_fn(forecaster_factory: Callable, *,
                     epochs: int = 1, batch_size: int = 32,
                     out_dir: Optional[str] = None) -> Callable:
    """A :class:`ContinuousTrainingLoop` ``train_fn`` that fits a fresh
    Chronos forecaster on each streaming window and returns the
    serialized ``.zoo`` artifact (servable by any replica via
    ``InferenceModel.load``). The forecaster trains through the guarded
    jitted step, so a poison window raises ``TrainingDiverged`` into
    the loop's demotion path instead of publishing a NaN model."""
    import tempfile

    def train(window):
        f = forecaster_factory()
        f.fit(window, epochs=epochs, batch_size=batch_size)
        d = out_dir or tempfile.mkdtemp(prefix="zoo-continuous-")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "model.zoo")
        f.model.save(path)
        return path

    return train
