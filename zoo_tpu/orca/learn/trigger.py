"""Trigger DSL for checkpoint/validation cadence.

Rebuild of ``pyzoo/zoo/orca/learn/trigger.py:19`` and Scala
``common/ZooTrigger.scala:43-154`` (EveryEpoch, SeveralIteration,
MaxIteration, MaxEpoch, And, Or). A trigger is consulted with the current
(epoch, iteration) counters; epoch triggers fire at epoch boundaries.
"""

from __future__ import annotations


class Trigger:
    def fire_on_epoch(self, epoch: int) -> bool:
        return False

    def fire_on_iteration(self, iteration: int) -> bool:
        return False

    @staticmethod
    def convert_trigger(t):
        if t is None or isinstance(t, Trigger):
            return t
        raise ValueError(f"not a trigger: {t}")


class EveryEpoch(Trigger):
    """Fire at every epoch end (reference: ``ZooTrigger.scala`` EveryEpoch)."""

    def fire_on_epoch(self, epoch: int) -> bool:
        return True


class SeveralIteration(Trigger):
    """Fire every ``interval`` iterations (reference: SeveralIteration)."""

    def __init__(self, interval: int):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.interval = int(interval)

    def fire_on_iteration(self, iteration: int) -> bool:
        return iteration > 0 and iteration % self.interval == 0


class MaxEpoch(Trigger):
    """End-condition trigger: fires once ``max`` epochs completed."""

    def __init__(self, max_epoch: int):
        self.max_epoch = int(max_epoch)

    def fire_on_epoch(self, epoch: int) -> bool:
        return epoch >= self.max_epoch


class MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = int(max_iteration)

    def fire_on_iteration(self, iteration: int) -> bool:
        return iteration >= self.max_iteration


class And(Trigger):
    def __init__(self, first: Trigger, *others: Trigger):
        self.triggers = (first,) + others

    def fire_on_epoch(self, epoch: int) -> bool:
        return all(t.fire_on_epoch(epoch) for t in self.triggers)

    def fire_on_iteration(self, iteration: int) -> bool:
        return all(t.fire_on_iteration(iteration) for t in self.triggers)


class Or(Trigger):
    def __init__(self, first: Trigger, *others: Trigger):
        self.triggers = (first,) + others

    def fire_on_epoch(self, epoch: int) -> bool:
        return any(t.fire_on_epoch(epoch) for t in self.triggers)

    def fire_on_iteration(self, iteration: int) -> bool:
        return any(t.fire_on_iteration(iteration) for t in self.triggers)
