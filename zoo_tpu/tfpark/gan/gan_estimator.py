"""Reference import path ``zoo.tfpark.gan.gan_estimator``
(``tfpark/gan/gan_estimator.py``) — the real implementation is the
orca GAN estimator (single-jit alternating G/D steps)."""

from zoo_tpu.orca.learn.gan import GANEstimator  # noqa: F401
