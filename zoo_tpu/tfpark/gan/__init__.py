"""Reference import path ``zoo.tfpark.gan`` (``tfpark/gan/``)."""

from zoo_tpu.tfpark.gan.gan_estimator import GANEstimator  # noqa: F401
