"""Parent of the ``keras`` alias package (the reference's
``tfpark/text/__init__.py`` is likewise empty — the model classes live
in ``zoo_tpu.tfpark.text.keras``)."""
