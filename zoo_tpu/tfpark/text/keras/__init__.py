"""Reference import-path alias (``pyzoo/zoo/tfpark/text/keras``):
``from zoo.tfpark.text.keras import NER`` works unmodified."""

from zoo_tpu.models.text import (  # noqa: F401
    CRF,
    IntentEntity,
    NER,
    SequenceTagger,
    crf_decode,
    crf_negative_log_likelihood,
)

__all__ = ["NER", "SequenceTagger", "IntentEntity", "CRF",
           "crf_decode", "crf_negative_log_likelihood"]
