"""Reference import path ``zoo.tfpark.text.estimator``
(``tfpark/text/estimator/`` — BERTClassifier/BERTNER/BERTSQuAD over the
TF1 estimator fabric). The TF1 ``model_fn`` fabric does not exist here;
BERT fine-tuning runs natively on the keras-facade ``BERT`` layer (the
bench's headline model). These adapters keep the reference's class names
importable: ``BERTClassifier`` builds that native fine-tune model, and
``bert_input_fn`` materializes the feature dicts it consumes."""

from __future__ import annotations

import numpy as np


class BERTClassifier:
    """reference ``bert_classifier.py:64`` — ``num_classes`` +
    checkpoint-dir ctor, ``train/evaluate/predict`` over input fns.
    Here: a keras-facade BERT classifier (CLS-token head) with the same
    train surface; pretrained TF checkpoint loading goes through the
    keras bridge, not TF1 init hooks."""

    def __init__(self, num_classes: int, bert_config_file=None,
                 init_checkpoint=None, use_one_hot_embeddings=False,
                 optimizer=None, model_dir=None,
                 vocab: int = 30522, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12,
                 seq_len: int = 128):
        from zoo_tpu.pipeline.api.keras import Sequential
        from zoo_tpu.pipeline.api.keras.layers import BERT, Dense, Lambda
        from zoo_tpu.pipeline.api.keras.optimizers import AdamWeightDecay

        if init_checkpoint is not None:
            raise NotImplementedError(
                "TF1 BERT checkpoint init is not wired; convert the "
                "checkpoint to a keras model and use "
                "bridges.keras_bridge, or fine-tune from scratch")
        m = Sequential()
        m.add(BERT(vocab=vocab, hidden_size=hidden_size, n_block=n_block,
                   n_head=n_head, seq_len=seq_len,
                   intermediate_size=4 * hidden_size,
                   max_position_len=max(seq_len, 512),
                   input_shape=(seq_len,)))
        m.add(Lambda(lambda h: h[:, 0], output_shape=(hidden_size,)))
        m.add(Dense(num_classes, activation="softmax"))
        m.compile(optimizer=optimizer or AdamWeightDecay(lr=2e-5),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        self.model = m
        self.seq_len = seq_len

    def train(self, input_fn, steps=None, batch_size: int = 32,
              epochs: int = 1):
        x, y = _materialize(input_fn)
        return self.model.fit(x, y, batch_size=batch_size,
                              nb_epoch=epochs, verbose=0)

    def evaluate(self, input_fn, eval_methods=("accuracy",),
                 batch_size: int = 32):
        x, y = _materialize(input_fn)
        return self.model.evaluate(x, y, batch_size=batch_size)

    def predict(self, input_fn, batch_size: int = 32):
        x, _ = _materialize(input_fn)
        return np.asarray(self.model.predict(x, batch_size=batch_size))


def bert_input_fn(data, max_seq_length: int, batch_size: int,
                  features_key: str = "input_ids", labels=None, **_):
    """reference ``bert_base.py:52`` built TF feed dicts from an RDD;
    here it normalizes (dict | (x, y) | ndarray) into the arrays the
    classifier consumes, returned as a thunk for API parity."""
    def fn():
        if isinstance(data, dict):
            x = np.asarray(data[features_key])
            y = np.asarray(data["label"]) if "label" in data else labels
        elif isinstance(data, tuple):
            x, y = np.asarray(data[0]), np.asarray(data[1])
        else:
            x, y = np.asarray(data), labels
        if x.shape[-1] != max_seq_length:
            raise ValueError(f"sequence length {x.shape[-1]} != "
                             f"max_seq_length {max_seq_length}")
        return x, y
    return fn


def _materialize(input_fn):
    out = input_fn() if callable(input_fn) else input_fn
    if isinstance(out, tuple):
        return out
    return out, None
