"""``zoo_tpu.tfpark`` — reference-import-path aliases.

The reference's TFPark (TF1-graphs-on-BigDL: TFOptimizer, TFDataset,
KerasModel, ``tfpark/tf_optimizer.py:350``) is declared obsolete by the
no-JVM architecture (docs/migration.md); the capabilities live in the
Orca estimators and bridges. What survives under this name is the text
model family (``tfpark/text/keras``), so reference imports like
``from zoo.tfpark.text.keras import NER`` keep working through the
``zoo`` compat forwarder.
"""
