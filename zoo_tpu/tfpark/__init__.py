"""``zoo_tpu.tfpark`` — reference-import-path compat surface.

The reference's TFPark (TF1-graphs-on-BigDL: TFOptimizer, TFDataset,
KerasModel, ``tfpark/tf_optimizer.py:350``) is architecturally obsolete
here (docs/migration.md) but its *capabilities* are not: ``KerasModel``,
``TFDataset`` and ``GANEstimator`` delegate onto the Orca fabric
(``tfpark/compat.py``), ``TFOptimizer``/``TFEstimator`` train TF1
graphs for real (variables captured as a JAX params pytree, jax.grad of
the interpreted loss — round 5; ``ModeKeys``/``EstimatorSpec`` shims
replace the ``tf.estimator`` namespace TensorFlow 2.16 removed), and
the text model family (``tfpark/text/keras``) is the real
implementation — so reference imports like ``from zoo.tfpark import
KerasModel`` and ``from zoo.tfpark.text.keras import NER`` keep working
through the ``zoo`` compat forwarder.
"""

from zoo_tpu.tfpark.compat import (  # noqa: F401
    EstimatorSpec,
    GANEstimator,
    ModeKeys,
    KerasModel,
    TFDataset,
    TFEstimator,
    TFNet,
    TFOptimizer,
    TFParkMigrationError,
    TFPredictor,
    ZooOptimizer,
)

__all__ = ["KerasModel", "TFDataset", "TFEstimator", "GANEstimator",
           "TFNet", "TFOptimizer", "TFPredictor", "ZooOptimizer",
           "TFParkMigrationError", "ModeKeys", "EstimatorSpec"]
