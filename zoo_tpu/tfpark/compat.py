"""TFPark training-API compat surface.

The reference's TFPark (``pyzoo/zoo/tfpark``) is the TF1-graphs-on-BigDL
stack: ``KerasModel`` (``tfpark/model.py:31``) wraps a compiled tf.keras
model and trains it distributed, ``TFDataset`` (``tfpark/tf_dataset.py:121``)
is the placeholder-feed dataset facade, ``TFEstimator``
(``tfpark/estimator.py:30``) runs TF1 ``model_fn`` Estimators, and
``GANEstimator`` (``tfpark/gan``) alternates G/D training.

Here the *capabilities* already exist under Orca names, so this module is
real delegation, not stubs: ``KerasModel`` bridges a tf.keras model onto
the zoo_tpu keras facade (``bridges/keras_bridge.py``) and trains it with
the jitted fit fabric; ``TFDataset.from_ndarrays`` /
``from_tf_data_dataset`` / ``from_dataframe`` feed it; ``GANEstimator``
is the Orca GAN fabric (``orca/learn/gan.py``); ``TFOptimizer`` and
``TFEstimator`` (model_fn) train TF1 graphs for real on the
variable-capture + jax.grad machinery (round 5). Only the RDD/
placeholder-feed constructors raise migration errors that name their
replacement — never a bare ``ModuleNotFoundError``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from zoo_tpu.orca.learn.gan import GANEstimator  # re-export  # noqa: F401

__all__ = ["KerasModel", "TFDataset", "TFEstimator", "GANEstimator",
           "TFParkMigrationError", "ModeKeys", "EstimatorSpec"]


class TFParkMigrationError(NotImplementedError):
    """A TFPark surface whose mechanism (TF1 graphs on the JVM) does not
    exist here; the message names the migration target."""


def _is_facade_model(model) -> bool:
    from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
    return isinstance(model, KerasNet)


class KerasModel:
    """``zoo.tfpark.KerasModel`` — reference ``tfpark/model.py:31``.

    Accepts a COMPILED tf.keras model (converted through the structural
    keras bridge, optimizer/loss mapped like the TF2 estimator does) or a
    zoo_tpu keras-facade model directly. ``fit``/``evaluate``/``predict``
    run on the jitted TPU fabric; the reference's ``distributed=True``
    flag is accepted and ignored (distribution is the ambient mesh here,
    set via ``init_orca_context(mesh_axes=...)``)."""

    def __init__(self, model, model_dir: Optional[str] = None,
                 optimizer=None):
        if _is_facade_model(model):
            self.model = model
        else:
            from zoo_tpu.bridges.keras_bridge import convert_keras_model
            from zoo_tpu.orca.learn.tf2.estimator import (
                _convert_loss,
                _convert_optimizer,
            )

            zmodel = convert_keras_model(model)
            opt = optimizer if optimizer is not None else \
                getattr(model, "optimizer", None)
            loss = getattr(model, "loss", None)
            if loss is None:
                raise ValueError(
                    "KerasModel needs a compiled tf.keras model "
                    "(model.compile(...) first) or a compiled facade "
                    "model")
            zmodel.compile(optimizer=_convert_optimizer(opt),
                           loss=_convert_loss(loss))
            self.model = zmodel
        self.model_dir = model_dir

    # -- weights ---------------------------------------------------------
    def get_weights(self):
        return self.model.get_weights() \
            if hasattr(self.model, "get_weights") else self.model.params

    def set_weights(self, weights):
        if hasattr(self.model, "set_weights"):
            self.model.set_weights(weights)
        else:
            self.model.params = weights

    def save_weights(self, filepath, overwrite=True, save_format=None):
        self.model.save_weights(filepath)

    def load_weights(self, filepath, by_name=False):
        self.model.load_weights(filepath)

    def save_model(self, path, overwrite=True):
        self.model.save(path)

    @staticmethod
    def load_model(path):
        from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
        out = KerasModel.__new__(KerasModel)
        out.model = KerasNet.load(path)
        out.model_dir = None
        return out

    # -- train/eval/predict ---------------------------------------------
    @staticmethod
    def _unpack(x, y, batch_size):
        if isinstance(x, TFDataset):
            bs = x.batch_size if x.batch_size and x.batch_size > 0 \
                else batch_size
            return x.x, x.y, bs
        return x, y, batch_size

    def fit(self, x=None, y=None, batch_size=32, epochs=1,
            validation_data=None, distributed=False, **kwargs):
        x, y, batch_size = self._unpack(x, y, batch_size)
        if isinstance(validation_data, TFDataset):
            validation_data = (validation_data.x, validation_data.y)
        return self.model.fit(x, y, batch_size=batch_size,
                              nb_epoch=epochs,
                              validation_data=validation_data,
                              verbose=kwargs.get("verbose", 0))

    def evaluate(self, x=None, y=None, batch_per_thread=None,
                 distributed=False):
        x, y, bs = self._unpack(x, y, batch_per_thread or 32)
        return self.model.evaluate(x, y, batch_size=bs)

    def predict(self, x, batch_per_thread=None, distributed=False):
        x, _, bs = self._unpack(x, None, batch_per_thread or 256)
        return self.model.predict(x, batch_size=bs)

    def train_on_batch(self, x, y=None, sample_weight=None):
        h = self.model.fit(x, y, batch_size=len(np.asarray(x)),
                           nb_epoch=1, shuffle=False, verbose=0)
        return h["loss"][-1]

    def test_on_batch(self, x, y=None, sample_weight=None,
                      reset_metrics=True):
        return self.model.evaluate(x, y, batch_size=len(np.asarray(x)))

    def predict_on_batch(self, x):
        return self.model.predict(x, batch_size=len(np.asarray(x)))


class TFDataset:
    """``zoo.tfpark.TFDataset`` — reference ``tfpark/tf_dataset.py:121``.

    The reference builds TF1 placeholder feeds over RDDs; here the
    constructors that have a data-capability equivalent materialize to
    numpy (the jitted fit fabric stages device-side), and the RDD/TF1
    ones raise a migration error naming the replacement."""

    # graph → TFDataset that created placeholders in it, so
    # TFOptimizer.from_loss can find the feed the way the reference's
    # ``_get_dataset_from_loss`` walks the graph (``tf_optimizer.py``)
    _placeholder_registry: "weakref.WeakValueDictionary" = None

    def __init__(self, x, y=None, batch_size: int = -1,
                 batch_per_thread: int = -1, val_x=None, val_y=None):
        self.x, self.y = x, y
        self.batch_size = batch_size if batch_size > 0 else batch_per_thread
        self.val_x, self.val_y = val_x, val_y
        self._tensors = None

    @property
    def tensors(self):
        """TF1 placeholders matching this dataset's arrays — the
        reference UX (``tf_dataset.py``): build the model on
        ``dataset.tensors``, then ``TFOptimizer.from_loss(loss, ...)``
        finds the dataset through the loss graph."""
        if self._tensors is None:
            import weakref

            import tensorflow as tf
            tf1 = tf.compat.v1

            graph = tf1.get_default_graph()

            def ph(a, name):
                a = np.asarray(a)
                return tf1.placeholder(
                    tf.dtypes.as_dtype(a.dtype),
                    (None,) + tuple(a.shape[1:]), name=name)

            def build(data, prefix):
                if isinstance(data, (tuple, list)):
                    return tuple(ph(a, f"{prefix}_{i}")
                                 for i, a in enumerate(data))
                return ph(data, prefix)

            x_t = build(self.x, "zoo_feature")
            if self.y is not None:
                self._tensors = (x_t, build(self.y, "zoo_label"))
            else:
                self._tensors = x_t
            if TFDataset._placeholder_registry is None:
                TFDataset._placeholder_registry = {}
            TFDataset._placeholder_registry.setdefault(
                weakref.ref(graph), []).append(weakref.ref(self))
        return self._tensors

    def _flat_placeholders(self):
        import tensorflow as tf
        flat = tf.nest.flatten(self._tensors) if self._tensors else []
        return {t.op.name for t in flat}

    @staticmethod
    def _from_graph(graph, loss=None) -> "Optional[TFDataset]":
        """Find the dataset whose placeholders feed ``loss`` — multiple
        datasets can register placeholders in one graph (train + val),
        so ancestry of the loss disambiguates, like the reference's
        ``_get_dataset_from_loss`` graph walk."""
        reg = TFDataset._placeholder_registry
        if reg is None:
            return None
        candidates = []
        for gref, dsets in list(reg.items()):
            if gref() is None:
                del reg[gref]  # graph was GC'd
                continue
            if gref() is not graph:
                continue
            candidates = [d() for d in dsets if d() is not None]
        if not candidates:
            return None
        if len(candidates) == 1 or loss is None:
            return candidates[-1]
        # ops feeding the loss
        seen, stack = set(), [loss.op]
        while stack:
            op = stack.pop()
            if op.name in seen:
                continue
            seen.add(op.name)
            stack.extend(t.op for t in op.inputs)
        feeding = [d for d in candidates
                   if d._flat_placeholders() and
                   d._flat_placeholders() <= seen]
        if len(feeding) == 1:
            return feeding[0]
        raise ValueError(
            "could not uniquely locate the TFDataset feeding this loss "
            f"({len(feeding)} of {len(candidates)} registered datasets "
            "feed it); pass dataset= explicitly to from_loss")

    @staticmethod
    def from_ndarrays(tensors, batch_size: int = -1,
                      batch_per_thread: int = -1, val_tensors=None,
                      **kwargs) -> "TFDataset":
        """reference: ``tf_dataset.py:384`` — (features, labels) ndarray
        tuples (or a single features array/tuple)."""
        def split(t):
            if isinstance(t, (tuple, list)) and len(t) == 2:
                return t[0], t[1]
            return t, None
        x, y = split(tensors)
        vx, vy = split(val_tensors) if val_tensors is not None \
            else (None, None)
        return TFDataset(x, y, batch_size, batch_per_thread, vx, vy)

    @staticmethod
    def from_tf_data_dataset(dataset, batch_size: int = -1,
                             batch_per_thread: int = -1,
                             **kwargs) -> "TFDataset":
        """reference: ``tf_dataset.py:601`` — materializes a (finite)
        ``tf.data.Dataset`` of (features, labels) to numpy; the fit
        fabric re-batches device-side."""
        xs, ys = [], []
        for item in dataset.as_numpy_iterator():
            if isinstance(item, (tuple, list)) and len(item) == 2:
                xs.append(np.asarray(item[0]))
                ys.append(np.asarray(item[1]))
            else:
                xs.append(np.asarray(item))
        if not xs:
            raise ValueError("from_tf_data_dataset got an empty dataset")
        x = np.stack(xs)
        y = np.stack(ys) if ys else None
        return TFDataset(x, y, batch_size, batch_per_thread)

    @staticmethod
    def from_dataframe(df, feature_cols: Sequence[str],
                       labels_cols: Optional[Sequence[str]] = None,
                       batch_size: int = -1, batch_per_thread: int = -1,
                       **kwargs) -> "TFDataset":
        """reference: ``tf_dataset.py:641`` — Spark DataFrame via the
        staging-dir ingestion (``orca/data/spark.py``), pandas directly."""
        import pandas as pd

        from zoo_tpu.orca.data.spark import (
            is_spark_dataframe,
            spark_dataframe_to_shards,
        )

        labels_cols = list(labels_cols or [])
        if is_spark_dataframe(df):
            shards = spark_dataframe_to_shards(df, feature_cols,
                                               labels_cols)
            parts = shards.collect()
            x = np.concatenate([p["x"] for p in parts])
            y = np.concatenate([p["y"] for p in parts]) \
                if labels_cols else None
        elif isinstance(df, pd.DataFrame):
            x = df[list(feature_cols)].to_numpy()
            if x.shape[1] == 1:
                x = x[:, 0]
            y = df[labels_cols].to_numpy() if labels_cols else None
            if y is not None and y.shape[1] == 1:
                y = y[:, 0]
        else:
            raise TypeError(f"from_dataframe expects a Spark or pandas "
                            f"DataFrame, got {type(df).__name__}")
        return TFDataset(x, y, batch_size, batch_per_thread)

    # -- TF1/RDD-mechanism constructors: migration errors ----------------
    @staticmethod
    def _migration(name: str, target: str):
        raise TFParkMigrationError(
            f"TFDataset.{name} fed TF1 placeholder graphs from RDDs — a "
            f"mechanism the no-JVM architecture removed. Use {target} "
            "(docs/migration.md, 'Spark DataFrame ingestion' / 'data "
            "layer').")

    @staticmethod
    def from_rdd(*args, **kwargs):
        TFDataset._migration(
            "from_rdd",
            "XShards (zoo.orca.data) or TFDataset.from_ndarrays")

    @staticmethod
    def from_string_rdd(*args, **kwargs):
        TFDataset._migration("from_string_rdd",
                             "orca.data pandas readers + TextSet")

    @staticmethod
    def from_bytes_rdd(*args, **kwargs):
        TFDataset._migration("from_bytes_rdd", "orca.data readers")

    @staticmethod
    def from_image_set(*args, **kwargs):
        TFDataset._migration(
            "from_image_set",
            "zoo.feature.image ImageSet + estimator fit on its arrays")

    @staticmethod
    def from_text_set(*args, **kwargs):
        TFDataset._migration(
            "from_text_set",
            "zoo.feature.text TextSet + estimator fit on its arrays")

    @staticmethod
    def from_feature_set(*args, **kwargs):
        TFDataset._migration(
            "from_feature_set",
            "orca.data FeatureSet tiers (orca/data/cache.py)")

    @staticmethod
    def from_tfrecord_file(*args, **kwargs):
        TFDataset._migration(
            "from_tfrecord_file",
            "zoo.orca.data.tfrecord.read_tfrecords (CRC-checked native "
            "reader)")


class ModeKeys:
    """``tf.estimator.ModeKeys`` replacement — TensorFlow removed
    ``tf.estimator`` entirely in 2.16+, so model_fn code must import
    these from ``zoo.tfpark`` now (same string values as TF1)."""

    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "infer"


class EstimatorSpec:
    """``tf.estimator.EstimatorSpec`` replacement (see ModeKeys): the
    (mode, predictions, loss, train_op) contract a model_fn returns."""

    def __init__(self, mode, predictions=None, loss=None, train_op=None,
                 eval_metric_ops=None, **_):
        self.mode = mode
        self.predictions = predictions
        self.loss = loss
        self.train_op = train_op
        self.eval_metric_ops = eval_metric_ops


class TFEstimator:
    """``zoo.tfpark.TFEstimator`` — reference ``tfpark/estimator.py:30``:
    TF1 ``model_fn`` Estimators. The reference ran them on the JVM
    fabric; here the model_fn builds a TF1 graph whose variables are
    captured as a JAX params pytree and trained with ``jax.grad`` of
    the interpreted loss (the same machinery as
    ``Estimator.from_graph``).

    One migration note is forced by TensorFlow itself: ``tf.estimator``
    was REMOVED from TF 2.16+, so a reference model_fn's
    ``tf.estimator.EstimatorSpec``/``ModeKeys`` references must become
    ``zoo.tfpark.EstimatorSpec``/``ModeKeys`` (same shapes/values).
    ``input_fn`` returns a ``TFDataset`` exactly as in the reference.
    """

    def __init__(self, model_fn, params: Optional[dict] = None,
                 model_dir: Optional[str] = None, config=None):
        self.model_fn = model_fn
        self.params = params
        self.model_dir = model_dir
        self._trained: Optional[dict] = None  # node name -> ndarray

    @classmethod
    def from_model_fn(cls, model_fn, model_dir: Optional[str] = None,
                      config=None, params: Optional[dict] = None,
                      warm_start_from=None):
        if warm_start_from is not None:
            raise TFParkMigrationError(
                "warm_start_from: load the source checkpoint into the "
                "session yourself and pass its values via model_fn")
        return cls(model_fn, params=params, model_dir=model_dir,
                   config=config)

    # -- internals --------------------------------------------------------
    def _call_model_fn(self, features, labels, mode):
        import inspect

        sig = inspect.signature(self.model_fn)
        # the tf.estimator contract: labels/mode/params/config are all
        # OPTIONAL parameters — pass only what the signature declares
        kwargs = {"features": features}
        if "labels" in sig.parameters:
            kwargs["labels"] = labels
        if "mode" in sig.parameters:
            kwargs["mode"] = mode
        if "params" in sig.parameters:
            kwargs["params"] = self.params
        if "config" in sig.parameters:
            kwargs["config"] = None
        spec = self.model_fn(**kwargs)
        if not isinstance(spec, EstimatorSpec):
            raise TypeError(
                "model_fn must return zoo.tfpark.EstimatorSpec "
                "(tf.estimator was removed from TensorFlow 2.16+; "
                f"got {type(spec).__name__})")
        return spec

    def _build(self, input_fn, mode):
        """Run input_fn + model_fn in a fresh TF1 graph; capture."""
        import tensorflow as tf

        from zoo_tpu.bridges.tf_graph import capture_trainable_graph
        tf1 = tf.compat.v1

        graph = tf1.Graph()
        with graph.as_default():
            ds = input_fn()
            if not isinstance(ds, TFDataset):
                raise TypeError(
                    "input_fn must return a zoo.tfpark.TFDataset "
                    f"(the reference contract); got {type(ds).__name__}")
            tensors = ds.tensors
            if isinstance(tensors, tuple) and len(tensors) == 2 \
                    and ds.y is not None:
                features, labels = tensors
            else:
                features, labels = tensors, None
            spec = self._call_model_fn(
                features, labels if mode != ModeKeys.PREDICT else None,
                mode)
            feats = list(features) if isinstance(features, (tuple, list)) \
                else [features]
            lbls = [] if labels is None or mode == ModeKeys.PREDICT else (
                list(labels) if isinstance(labels, (tuple, list))
                else [labels])
            preds = spec.predictions
            pred_keys, outputs = None, []
            if isinstance(preds, dict):
                pred_keys = list(preds)
                outputs = [preds[k] for k in pred_keys]
            elif preds is not None:
                outputs = [preds]
            metrics = None
            if getattr(spec, "eval_metric_ops", None):
                # TF metric ops are (value, update_op) pairs; raw value
                # tensors are accepted too
                metrics = {k: (v[0] if isinstance(v, (tuple, list))
                               else v)
                           for k, v in spec.eval_metric_ops.items()}
            trainable, sess, tf_vars = capture_trainable_graph(
                inputs=feats, labels=lbls, loss=spec.loss,
                outputs=outputs, metrics=metrics)
        # TFEstimator owns no write-back session (weights travel by
        # name through self._trained); release the capture session
        sess.close()
        if self._trained:
            # carry weights across per-mode graphs by VARIABLE NAME —
            # the role tf.estimator's checkpoint round trip played
            for name, val in self._trained.items():
                if name in trainable.params:
                    trainable.params[name] = val
        return ds, spec, trainable, pred_keys

    @staticmethod
    def _arrays(ds):
        xs = [np.asarray(a) for a in (
            ds.x if isinstance(ds.x, (tuple, list)) else [ds.x])]
        ys = [] if ds.y is None else [np.asarray(a) for a in (
            ds.y if isinstance(ds.y, (tuple, list)) else [ds.y])]
        bs = ds.batch_size if ds.batch_size and ds.batch_size > 0 else 32
        return xs, ys, bs

    # -- reference API ----------------------------------------------------
    def train(self, input_fn, steps: Optional[int] = None):
        from zoo_tpu.bridges.tf_graph import optimizer_from_train_op
        from zoo_tpu.orca.learn.tf2.graph_estimator import GraphTrainer

        ds, spec, trainable, _ = self._build(input_fn, ModeKeys.TRAIN)
        if spec.loss is None:
            raise ValueError("model_fn returned no loss in TRAIN mode")
        optim = "adam"
        if spec.train_op is not None:
            optim = optimizer_from_train_op(
                trainable.graph_def,
                getattr(spec.train_op, "name", spec.train_op))
        trainer = GraphTrainer(trainable, optim)
        xs, ys, bs = self._arrays(ds)
        n = xs[0].shape[0]
        steps_per_epoch = max(1, n // bs)
        epochs = max(1, -(-(steps or steps_per_epoch) // steps_per_epoch))
        trainer.fit(xs, ys, epochs=epochs, batch_size=bs,
                    max_steps=steps)
        self._trained = trainer.numpy_params()
        return self

    def evaluate(self, input_fn, eval_methods=None,
                 steps: Optional[int] = None, checkpoint_path=None):
        from zoo_tpu.orca.learn.tf2.graph_estimator import GraphTrainer

        ds, spec, trainable, _ = self._build(input_fn, ModeKeys.EVAL)
        trainer = GraphTrainer(trainable, "adam")
        xs, ys, bs = self._arrays(ds)
        return trainer.evaluate(xs, ys, batch_size=bs)

    def predict(self, input_fn, predict_keys=None, checkpoint_path=None):
        from zoo_tpu.orca.learn.tf2.graph_estimator import GraphTrainer

        ds, spec, trainable, pred_keys = self._build(input_fn,
                                                     ModeKeys.PREDICT)
        if spec.predictions is None:
            raise ValueError(
                "model_fn returned no predictions in PREDICT mode")
        trainer = GraphTrainer(trainable, "adam")
        xs, _ys, bs = self._arrays(ds)
        # dict predictions come back as ONE output array — the requested
        # key when predict_keys names it
        if predict_keys is not None:
            keys = [predict_keys] if isinstance(predict_keys, str) \
                else list(predict_keys)
            if pred_keys is None:
                raise ValueError(
                    "predict_keys given but model_fn returned a single "
                    "tensor, not a dict of predictions")
            unknown = [k for k in keys if k not in pred_keys]
            if unknown:
                raise ValueError(
                    f"unknown predict_keys {unknown}; model_fn "
                    f"predictions has {pred_keys}")
            if len(keys) != 1:
                raise NotImplementedError(
                    "one predict_keys entry at a time (the rebuild "
                    "returns a single array per predict call)")
            trainable.output_refs = [
                trainable.output_refs[pred_keys.index(keys[0])]]
        return trainer.predict(xs, batch_size=bs)


class TFNet:
    """``zoo.tfpark.TFNet`` — reference ``tfpark/tfnet.py`` (frozen-graph
    inference as a layer). Delegates to the GraphDef→JAX interpreter."""

    @staticmethod
    def from_export_folder(folder: str):
        from zoo_tpu.pipeline.api.net import Net
        return Net.load_tf(folder)

    @staticmethod
    def from_session(sess, inputs, outputs, generate_backward=False):
        import tempfile

        from zoo_tpu.pipeline.api.net import Net
        from zoo_tpu.util.tf import export_tf

        folder = tempfile.mkdtemp(prefix="zoo_tfnet_")
        export_tf(sess, folder, inputs=inputs, outputs=outputs)
        return Net.load_tf(folder)


class ZooOptimizer:
    """``zoo.tfpark.ZooOptimizer`` — reference ``zoo_optimizer.py``
    wrapped a tf.train.Optimizer to tag gradients for the JVM fabric.
    No JVM fabric here: it is the identity on the wrapped optimizer so
    reference model-building code keeps running."""

    def __new__(cls, optimizer, *args, **kwargs):
        return optimizer


class TFOptimizer:
    """``zoo.tfpark.TFOptimizer`` — reference ``tf_optimizer.py:350``:
    train a TF1 session graph distributed. The reference exports the
    graph to the JVM/BigDL fabric; here the graph's variables are
    captured as a JAX params pytree and the interpreted loss is
    differentiated with ``jax.grad`` on the mesh
    (``orca/learn/tf2/graph_estimator.GraphTrainer``). After
    ``optimize()`` the trained weights are written back into the user's
    session, so their saver/export flow keeps working."""

    def __init__(self, trainer, dataset: "TFDataset", sess, tf_vars,
                 batch_size: Optional[int] = None):
        self._trainer = trainer
        self._dataset = dataset
        self.sess = sess
        self._tf_vars = tf_vars
        self._batch_size = batch_size if batch_size else (
            dataset.batch_size if dataset is not None
            and dataset.batch_size and dataset.batch_size > 0 else 32)
        self.estimator = None  # reference parity attribute

    @staticmethod
    def _capture(loss, optim_method, session, inputs, labels, dataset,
                 metrics, clip_norm, clip_value, tensor_with_value):
        from zoo_tpu.bridges.tf_graph import capture_trainable_graph
        from zoo_tpu.orca.learn.tf2.graph_estimator import GraphTrainer

        if tensor_with_value:
            raise TFParkMigrationError(
                "tensor_with_value fed phase-dependent placeholders "
                "(train vs validation constants); bake the training "
                "value into the graph or make it a model input")
        if dataset is None and inputs is None:
            dataset = TFDataset._from_graph(loss.graph, loss)
            if dataset is None:
                raise ValueError(
                    "from_loss could not locate a TFDataset for this "
                    "graph: build the model on dataset.tensors, or pass "
                    "inputs=/dataset= explicitly")
        if inputs is None:
            inputs = dataset.tensors
        # reference semantics (tf_optimizer.py:553): a 2-tuple of inputs
        # IS the (features, labels) structure
        if labels is None and isinstance(inputs, tuple) \
                and len(inputs) == 2:
            inputs, labels = inputs
        ins = list(inputs) if isinstance(inputs, (tuple, list)) \
            else [inputs]
        lbs = [] if labels is None else (
            list(labels) if isinstance(labels, (tuple, list))
            else [labels])
        trainable, sess, tf_vars = capture_trainable_graph(
            inputs=ins, labels=lbs, loss=loss, metrics=metrics,
            sess=session)
        trainer = GraphTrainer(trainable, optim_method,
                               clip_norm=clip_norm,
                               clip_value=clip_value)
        return trainer, dataset, sess, tf_vars

    @classmethod
    def from_loss(cls, loss, optim_method, session=None, inputs=None,
                  dataset=None, val_outputs=None, val_labels=None,
                  val_method=None, clip_norm=None, clip_value=None,
                  metrics=None, tensor_with_value=None,
                  session_config=None, model_dir=None, updates=None):
        """reference ``tf_optimizer.py:514`` — the loss tensor must come
        from a graph built on ``TFDataset.tensors`` (or pass ``inputs=``
        + ``dataset=``)."""
        if updates:
            import logging
            logging.getLogger(__name__).warning(
                "from_loss(updates=...): update ops are captured frozen "
                "— running stats will not advance during training")
        trainer, dataset, sess, tf_vars = cls._capture(
            loss, optim_method, session, inputs, None, dataset, metrics,
            clip_norm, clip_value, tensor_with_value)
        return cls(trainer, dataset, sess, tf_vars)

    @classmethod
    def from_train_op(cls, train_op, loss, *, inputs=None, labels=None,
                      metrics=None, updates=None, sess=None,
                      dataset=None, tensor_with_value=None,
                      session_config=None, model_dir=None):
        """reference ``tf_optimizer.py:464`` — recovers the optimizer
        family + hyperparameters from the ``Apply*`` ops behind the
        train_op (``bridges/tf_graph.optimizer_from_train_op``); raises
        ``NotImplementedError`` for unrecognized optimizers or
        non-constant learning rates."""
        from zoo_tpu.bridges.tf_graph import optimizer_from_train_op

        optim = optimizer_from_train_op(
            loss.graph.as_graph_def(),
            getattr(train_op, "name", train_op))
        trainer, dataset, sess_, tf_vars = cls._capture(
            loss, optim, sess, inputs, labels, dataset, metrics,
            None, None, tensor_with_value)
        return cls(trainer, dataset, sess_, tf_vars)

    @classmethod
    def from_keras(cls, keras_model, dataset, session=None,
                   model_dir=None, metrics=None, **kwargs):
        raise TFParkMigrationError(
            "TFOptimizer.from_keras: use zoo.tfpark.KerasModel (same "
            "capability, structural bridge) — see docs/migration.md")

    # -- the reference train entrypoint ----------------------------------
    def optimize(self, end_trigger=None, batch_size: Optional[int] = None,
                 checkpoint_trigger=None):
        from zoo_tpu.bridges.tf_graph import write_back_variables
        from zoo_tpu.orca.learn.trigger import MaxEpoch, MaxIteration

        if self._dataset is None:
            raise ValueError(
                "optimize() needs the TFDataset the graph was built on "
                "(from_loss located none and no dataset= was passed)")
        bs = int(batch_size or self._batch_size or 32)
        xs = [np.asarray(a) for a in (
            self._dataset.x if isinstance(self._dataset.x, (tuple, list))
            else [self._dataset.x])]
        ys = [] if self._dataset.y is None else [
            np.asarray(a) for a in (
                self._dataset.y
                if isinstance(self._dataset.y, (tuple, list))
                else [self._dataset.y])]
        n = xs[0].shape[0]
        max_steps = None
        if end_trigger is None:
            epochs = 1
        elif isinstance(end_trigger, MaxEpoch):
            epochs = end_trigger.max_epoch
        elif isinstance(end_trigger, MaxIteration):
            # exact iteration budget, not rounded up to whole epochs
            max_steps = end_trigger.max_iteration
            steps_per_epoch = max(1, n // bs)
            epochs = max(1, -(-max_steps // steps_per_epoch))
        else:
            raise ValueError(
                f"unsupported end_trigger {type(end_trigger).__name__}; "
                "use MaxEpoch(n) or MaxIteration(n)")
        hist = self._trainer.fit(xs, ys, epochs=epochs, batch_size=bs,
                                 max_steps=max_steps)
        write_back_variables(self.sess, self._tf_vars,
                             self._trainer.numpy_params())
        return hist


class TFPredictor:
    """``zoo.tfpark.TFPredictor`` — reference ``tf_predictor.py`` ran
    TF1 session fetches distributed. Frozen graphs predict through
    TFNet/InferenceModel instead."""

    _MSG = ("TFPredictor ran TF1 session fetches on the JVM — export "
            "the graph and predict through zoo.tfpark.TFNet"
            ".from_export_folder or zoo.pipeline.inference"
            ".InferenceModel; see docs/migration.md")

    def __init__(self, *args, **kwargs):
        raise TFParkMigrationError(self._MSG)

    @classmethod
    def from_outputs(cls, *a, **k):
        raise TFParkMigrationError(cls._MSG)

    @classmethod
    def from_keras(cls, *a, **k):
        raise TFParkMigrationError(cls._MSG)
