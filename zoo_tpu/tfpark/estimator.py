"""Reference import path ``zoo.tfpark.estimator`` (``tfpark/estimator.py:30``)."""

from zoo_tpu.tfpark.compat import TFEstimator  # noqa: F401
