"""Keras-2-style API variant (reference: ``pyzoo/zoo/pipeline/api/keras2``).

The reference ships a second layer namespace with Keras-2 argument
conventions (``units``/``filters``/``kernel_size``/``strides``/
``padding``/``use_bias``/``kernel_initializer``) alongside the Keras-1
API. Here each keras2 symbol is a thin adapter that translates the
Keras-2 argument names onto the corresponding Keras-1 layer from
``zoo_tpu.pipeline.api.keras`` — one engine, two façades, exactly the
reference's structure (its keras2 layers also compile to the same Scala
modules underneath).
"""

from zoo_tpu.pipeline.api.keras.engine.topology import (  # noqa: F401
    Input,
    Model,
    Sequential,
)
from zoo_tpu.pipeline.api.keras2 import layers  # noqa: F401

__all__ = ["Input", "Model", "Sequential", "layers"]
