"""Keras-2 layer façade (reference: ``pyzoo/zoo/pipeline/api/keras2/layers``:
core/convolutional/pooling/merge/local/embeddings/advanced_activations/
convolutional_recurrent). Each function returns the equivalent Keras-1
layer with arguments translated; graphs/Sequentials mix both façades
freely because the layer objects are the same type underneath."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

from zoo_tpu.pipeline.api.keras import layers as k1
from zoo_tpu.pipeline.api.keras.layers.core import merge as _merge

__all__ = [
    "Dense", "Activation", "Dropout", "Flatten", "Embedding",
    "Conv1D", "Conv2D", "Cropping1D", "SeparableConv2D",
    "MaxPooling1D", "AveragePooling1D", "MaxPooling2D",
    "AveragePooling2D", "GlobalAveragePooling1D", "GlobalMaxPooling1D",
    "GlobalAveragePooling2D", "GlobalMaxPooling2D",
    "Maximum", "Minimum", "Average", "Add", "Concatenate",
    "average", "maximum", "minimum",
    "LocallyConnected1D", "LeakyReLU", "ELU", "ThresholdedReLU",
    "ConvLSTM2D", "BatchNormalization", "LSTM", "GRU", "SimpleRNN",
]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1] if len(v) > 1 else v[0])
    return int(v), int(v)


def _df(data_format: Optional[str]) -> str:
    """keras2 data_format -> keras1 dim_ordering."""
    if data_format in (None, "channels_last"):
        return "tf"
    if data_format == "channels_first":
        return "th"
    raise ValueError(f"unknown data_format {data_format!r}")


# ------------------------------------------------------------------ core

def Dense(units: int, kernel_initializer="glorot_uniform",
          bias_initializer="zero", activation=None,
          kernel_regularizer=None, bias_regularizer=None,
          use_bias: bool = True, input_dim: Optional[int] = None,
          input_shape=None, name: Optional[str] = None, **kwargs):
    """reference: ``keras2/layers/core.py:26``."""
    return k1.Dense(units, init=kernel_initializer, activation=activation,
                    bias=use_bias, W_regularizer=kernel_regularizer,
                    b_regularizer=bias_regularizer, input_dim=input_dim,
                    input_shape=input_shape, name=name, **kwargs)


def Activation(activation, input_shape=None, name=None, **kwargs):
    return k1.Activation(activation, input_shape=input_shape, name=name,
                         **kwargs)


def Dropout(rate: float, input_shape=None, name=None, **kwargs):
    """keras2 ``rate`` == keras1 ``p``."""
    return k1.Dropout(p=rate, input_shape=input_shape, name=name, **kwargs)


def Flatten(input_shape=None, name=None, **kwargs):
    return k1.Flatten(input_shape=input_shape, name=name, **kwargs)


def Embedding(input_dim: int, output_dim: int,
              embeddings_initializer="uniform", input_length=None,
              input_shape=None, name=None, **kwargs):
    """reference: ``keras2/layers/embeddings.py``."""
    if input_shape is None and input_length is not None:
        input_shape = (input_length,)
    return k1.Embedding(input_dim, output_dim,
                        init=embeddings_initializer,
                        input_shape=input_shape, name=name, **kwargs)


# --------------------------------------------------------- convolutional

def Conv1D(filters: int, kernel_size: int, strides: int = 1,
           padding: str = "valid", activation=None, use_bias: bool = True,
           kernel_initializer="glorot_uniform", input_shape=None,
           name=None, **kwargs):
    """reference: ``keras2/layers/convolutional.py:24``."""
    return k1.Conv1D(filters, kernel_size, subsample_length=strides,
                     border_mode=padding, activation=activation,
                     bias=use_bias, init=kernel_initializer,
                     input_shape=input_shape, name=name, **kwargs)


def Conv2D(filters: int, kernel_size, strides=(1, 1),
           padding: str = "valid", data_format=None, activation=None,
           use_bias: bool = True, kernel_initializer="glorot_uniform",
           input_shape=None, name=None, **kwargs):
    """reference: ``keras2/layers/convolutional.py:100``."""
    kh, kw = _pair(kernel_size)
    return k1.Conv2D(filters, kh, kw, subsample=_pair(strides),
                     border_mode=padding, dim_ordering=_df(data_format),
                     activation=activation, bias=use_bias,
                     init=kernel_initializer, input_shape=input_shape,
                     name=name, **kwargs)


def SeparableConv2D(filters: int, kernel_size, strides=(1, 1),
                    padding: str = "valid", data_format=None,
                    depth_multiplier: int = 1, activation=None,
                    use_bias: bool = True, input_shape=None, name=None,
                    **kwargs):
    kh, kw = _pair(kernel_size)
    return k1.SeparableConvolution2D(
        filters, kh, kw, subsample=_pair(strides), border_mode=padding,
        dim_ordering=_df(data_format), depth_multiplier=depth_multiplier,
        activation=activation, bias=use_bias, input_shape=input_shape,
        name=name, **kwargs)


def Cropping1D(cropping=(1, 1), input_shape=None, name=None, **kwargs):
    """reference: ``keras2/layers/convolutional.py:196``."""
    return k1.Cropping1D(cropping=tuple(cropping),
                         input_shape=input_shape, name=name, **kwargs)


def LocallyConnected1D(filters: int, kernel_size: int, strides: int = 1,
                       padding: str = "valid", activation=None,
                       use_bias: bool = True, input_shape=None, name=None,
                       **kwargs):
    """reference: ``keras2/layers/local.py:23``."""
    return k1.LocallyConnected1D(
        filters, kernel_size, subsample_length=strides,
        border_mode=padding, activation=activation, bias=use_bias,
        input_shape=input_shape, name=name, **kwargs)


def ConvLSTM2D(filters: int, kernel_size, strides=(1, 1),
               padding: str = "same", data_format="channels_first",
               return_sequences: bool = False, input_shape=None,
               name=None, **kwargs):
    """reference: ``keras2/layers/convolutional_recurrent.py`` (its BigDL
    backend is channels-first only; same here)."""
    if _df(data_format) != "th":
        raise ValueError("ConvLSTM2D supports data_format="
                         "'channels_first' only (like the reference)")
    kh, _ = _pair(kernel_size)
    return k1.ConvLSTM2D(filters, kh, border_mode=padding,
                         subsample=_pair(strides),
                         return_sequences=return_sequences,
                         input_shape=input_shape, name=name, **kwargs)


# --------------------------------------------------------------- pooling

def MaxPooling1D(pool_size: int = 2, strides=None, padding="valid",
                 input_shape=None, name=None, **kwargs):
    """reference: ``keras2/layers/pooling.py:24``."""
    return k1.MaxPooling1D(pool_length=pool_size, stride=strides,
                           border_mode=padding, input_shape=input_shape,
                           name=name, **kwargs)


def AveragePooling1D(pool_size: int = 2, strides=None, padding="valid",
                     input_shape=None, name=None, **kwargs):
    return k1.AveragePooling1D(pool_length=pool_size, stride=strides,
                               border_mode=padding,
                               input_shape=input_shape, name=name,
                               **kwargs)


def MaxPooling2D(pool_size=(2, 2), strides=None, padding="valid",
                 data_format=None, input_shape=None, name=None, **kwargs):
    return k1.MaxPooling2D(pool_size=_pair(pool_size),
                           strides=_pair(strides) if strides else None,
                           border_mode=padding,
                           dim_ordering=_df(data_format),
                           input_shape=input_shape, name=name, **kwargs)


def AveragePooling2D(pool_size=(2, 2), strides=None, padding="valid",
                     data_format=None, input_shape=None, name=None,
                     **kwargs):
    return k1.AveragePooling2D(pool_size=_pair(pool_size),
                               strides=_pair(strides) if strides else None,
                               border_mode=padding,
                               dim_ordering=_df(data_format),
                               input_shape=input_shape, name=name,
                               **kwargs)


def GlobalAveragePooling1D(input_shape=None, name=None, **kwargs):
    """reference: ``keras2/layers/pooling.py:100``."""
    return k1.GlobalAveragePooling1D(input_shape=input_shape, name=name,
                                     **kwargs)


def GlobalMaxPooling1D(input_shape=None, name=None, **kwargs):
    return k1.GlobalMaxPooling1D(input_shape=input_shape, name=name,
                                 **kwargs)


def GlobalAveragePooling2D(data_format=None, input_shape=None, name=None,
                           **kwargs):
    """reference: ``keras2/layers/pooling.py:149``."""
    return k1.GlobalAveragePooling2D(dim_ordering=_df(data_format),
                                     input_shape=input_shape, name=name,
                                     **kwargs)


def GlobalMaxPooling2D(data_format=None, input_shape=None, name=None,
                       **kwargs):
    return k1.GlobalMaxPooling2D(dim_ordering=_df(data_format),
                                 input_shape=input_shape, name=name,
                                 **kwargs)


# ----------------------------------------------------------------- merge

class _MergeN:
    """keras2 functional merge layers (reference ``keras2/layers/merge.py``:
    ``Maximum``/``Minimum``/``Average``): instantiate, then call on a list
    of graph tensors."""

    mode: str = "sum"

    def __init__(self, name: Optional[str] = None):
        self.name = name

    def __call__(self, inputs: Sequence):
        return _merge(list(inputs), mode=self.mode, name=self.name)


class Maximum(_MergeN):
    mode = "max"


class Minimum(_MergeN):
    mode = "min"


class Average(_MergeN):
    mode = "ave"


class Add(_MergeN):
    mode = "sum"


class Concatenate(_MergeN):
    mode = "concat"

    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def __call__(self, inputs: Sequence):
        return _merge(list(inputs), mode="concat", concat_axis=self.axis,
                      name=self.name)


def average(inputs: Sequence, name: Optional[str] = None):
    """Functional alias (reference ``keras2/layers/merge.py`` ``average``)."""
    return Average(name=name)(inputs)


def maximum(inputs: Sequence, name: Optional[str] = None):
    """Functional alias (reference ``keras2/layers/merge.py`` ``maximum``)."""
    return Maximum(name=name)(inputs)


def minimum(inputs: Sequence, name: Optional[str] = None):
    """Functional alias (reference ``keras2/layers/merge.py`` ``minimum``)."""
    return Minimum(name=name)(inputs)


# ------------------------------------------------- advanced activations

def LeakyReLU(alpha: float = 0.3, input_shape=None, name=None, **kwargs):
    return k1.LeakyReLU(alpha=alpha, input_shape=input_shape, name=name,
                        **kwargs)


def ELU(alpha: float = 1.0, input_shape=None, name=None, **kwargs):
    return k1.ELU(alpha=alpha, input_shape=input_shape, name=name,
                  **kwargs)


def ThresholdedReLU(theta: float = 1.0, input_shape=None, name=None,
                    **kwargs):
    return k1.ThresholdedReLU(theta=theta, input_shape=input_shape,
                              name=name, **kwargs)


# ------------------------------------------------------------- recurrent

def LSTM(units: int, activation="tanh", recurrent_activation="sigmoid",
         return_sequences: bool = False, input_shape=None, name=None,
         **kwargs):
    return k1.LSTM(units, activation=activation,
                   inner_activation=recurrent_activation,
                   return_sequences=return_sequences,
                   input_shape=input_shape, name=name, **kwargs)


def GRU(units: int, activation="tanh", recurrent_activation="sigmoid",
        return_sequences: bool = False, input_shape=None, name=None,
        **kwargs):
    return k1.GRU(units, activation=activation,
                  inner_activation=recurrent_activation,
                  return_sequences=return_sequences,
                  input_shape=input_shape, name=name, **kwargs)


def SimpleRNN(units: int, activation="tanh",
              return_sequences: bool = False, input_shape=None, name=None,
              **kwargs):
    return k1.SimpleRNN(units, activation=activation,
                        return_sequences=return_sequences,
                        input_shape=input_shape, name=name, **kwargs)


def BatchNormalization(axis: int = -1, momentum: float = 0.99,
                       epsilon: float = 1e-3, input_shape=None, name=None,
                       **kwargs):
    if axis != -1:
        raise ValueError("BatchNormalization supports the trailing feature "
                         "axis only (axis=-1)")
    return k1.BatchNormalization(epsilon=epsilon, momentum=momentum,
                                 input_shape=input_shape, name=name,
                                 **kwargs)