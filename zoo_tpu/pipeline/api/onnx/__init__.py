from zoo_tpu.pipeline.api.onnx.onnx_loader import (  # noqa: F401
    OnnxGraphNet,
    load_onnx,
)
