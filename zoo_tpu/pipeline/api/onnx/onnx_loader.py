"""ONNX model loader — zero-dependency wire-format parser + JAX interpreter.

Rebuild of the reference's ONNX ingestion
(``pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:1`` + ~20 op mappers, which
build a BigDL layer graph). The ``onnx`` package is not available in this
environment, so the ModelProto is decoded directly from protobuf wire
format (field numbers per the public onnx.proto3 schema) with the same
minimal codec the TensorBoard writer uses, and the graph is interpreted in
JAX. Initializers become trainable params keyed by tensor name, so a
loaded ONNX model fine-tunes like any other (the reference's layer-graph
load had the same property).
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
from zoo_tpu.tensorboard import proto as wire

# --------------------------------------------------- proto field numbers
# ModelProto
_M_GRAPH = 7
# GraphProto
_G_NODE, _G_INITIALIZER, _G_INPUT, _G_OUTPUT = 1, 5, 11, 12
# NodeProto
_N_INPUT, _N_OUTPUT, _N_NAME, _N_OPTYPE, _N_ATTR = 1, 2, 3, 4, 5
# AttributeProto
_A_NAME, _A_F, _A_I, _A_S, _A_T, _A_FLOATS, _A_INTS = 1, 2, 3, 4, 5, 7, 8
# TensorProto
_T_DIMS, _T_DTYPE, _T_FLOAT, _T_INT32, _T_INT64, _T_NAME, _T_RAW = \
    1, 2, 4, 5, 7, 8, 9
# ValueInfoProto / TypeProto / TensorTypeProto / ShapeProto / Dimension
_VI_NAME, _VI_TYPE = 1, 2
_TY_TENSOR = 1
_TT_ELEM, _TT_SHAPE = 1, 2
_SH_DIM = 1
_DIM_VALUE = 1

_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
           7: np.int64, 9: np.bool_, 11: np.float64, 10: np.float16}


def _decode_packed_varints(buf: bytes) -> List[int]:
    out, pos = [], 0
    while pos < len(buf):
        v, pos = wire.decode_varint(buf, pos)
        out.append(v - (1 << 64) if v >= (1 << 63) else v)
    return out


def _parse_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    f = wire.parse_fields(buf)
    dims: List[int] = []
    for d in f.get(_T_DIMS, []):
        if isinstance(d, bytes):  # packed repeated (proto3 default)
            dims.extend(_decode_packed_varints(d))
        else:
            dims.append(int(d))
    dt = _DTYPES[int(f.get(_T_DTYPE, [1])[0])]
    name = f.get(_T_NAME, [b""])[0].decode()
    if _T_RAW in f:
        arr = np.frombuffer(f[_T_RAW][0], dtype=dt)
    elif _T_FLOAT in f:
        vals = f[_T_FLOAT]
        if len(vals) == 1 and isinstance(vals[0], bytes):  # packed
            arr = np.frombuffer(vals[0], dtype="<f4")
        else:
            arr = np.asarray(vals, np.float32)
    elif _T_INT64 in f:
        vals = f[_T_INT64]
        if len(vals) == 1 and isinstance(vals[0], bytes):
            arr = np.asarray(_decode_packed_varints(vals[0]), np.int64)
        else:
            arr = np.asarray([int(v) for v in vals], np.int64)
    elif _T_INT32 in f:
        vals = f[_T_INT32]
        if len(vals) == 1 and isinstance(vals[0], bytes):
            arr = np.frombuffer(vals[0], dtype="<i4")
        else:
            arr = np.asarray([int(v) for v in vals], np.int32)
    else:
        arr = np.zeros(0, dt)
    arr = arr.astype(dt, copy=False).reshape(dims)
    if arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    elif arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return name, arr


def _parse_attr(buf: bytes) -> Tuple[str, Any]:
    vals: Dict[int, List] = {}
    for field, wtype, val in wire.iter_fields(buf):
        vals.setdefault(field, []).append((wtype, val))
    name = vals[_A_NAME][0][1].decode()
    if _A_T in vals:
        return name, _parse_tensor(vals[_A_T][0][1])[1]
    if _A_INTS in vals:
        out = []
        for wt, v in vals[_A_INTS]:
            if wt == 2:
                out.extend(_decode_packed_varints(v))
            else:
                out.append(int(v))
        return name, out
    if _A_FLOATS in vals:
        out = []
        for wt, v in vals[_A_FLOATS]:
            if wt == 2:
                out.extend(np.frombuffer(v, "<f4").tolist())
            else:
                out.append(float(v))
        return name, out
    if _A_S in vals:
        return name, vals[_A_S][0][1].decode()
    if _A_F in vals:
        return name, float(vals[_A_F][0][1])
    if _A_I in vals:
        v = int(vals[_A_I][0][1])
        return name, v - (1 << 64) if v >= (1 << 63) else v
    return name, None


class _Node:
    def __init__(self, buf: bytes):
        f = wire.parse_fields(buf)
        self.inputs = [b.decode() for b in f.get(_N_INPUT, [])]
        self.outputs = [b.decode() for b in f.get(_N_OUTPUT, [])]
        self.name = f.get(_N_NAME, [b""])[0].decode()
        self.op = f.get(_N_OPTYPE, [b""])[0].decode()
        self.attrs = dict(_parse_attr(a) for a in f.get(_N_ATTR, []))


def _value_info_name(buf: bytes) -> str:
    return wire.parse_fields(buf).get(_VI_NAME, [b""])[0].decode()


def _value_info_shape(buf: bytes) -> Optional[Tuple]:
    f = wire.parse_fields(buf)
    if _VI_TYPE not in f:
        return None
    ty = wire.parse_fields(f[_VI_TYPE][0])
    if _TY_TENSOR not in ty:
        return None
    tt = wire.parse_fields(ty[_TY_TENSOR][0])
    if _TT_SHAPE not in tt:
        return None
    sh = wire.parse_fields(tt[_TT_SHAPE][0])
    dims = []
    for d in sh.get(_SH_DIM, []):
        df = wire.parse_fields(d)
        dims.append(int(df[_DIM_VALUE][0]) if _DIM_VALUE in df else None)
    return tuple(dims)


class OnnxGraph:
    """Parsed GraphProto: nodes + initializers + graph inputs/outputs."""

    def __init__(self, model_bytes: bytes):
        mf = wire.parse_fields(model_bytes)
        if _M_GRAPH not in mf:
            raise ValueError("not an ONNX ModelProto (no graph field)")
        gf = wire.parse_fields(mf[_M_GRAPH][0])
        self.nodes = [_Node(b) for b in gf.get(_G_NODE, [])]
        self.initializers: Dict[str, np.ndarray] = dict(
            _parse_tensor(b) for b in gf.get(_G_INITIALIZER, []))
        self.inputs = [_value_info_name(b) for b in gf.get(_G_INPUT, [])
                       if _value_info_name(b) not in self.initializers]
        self.input_shapes = [
            _value_info_shape(b) for b in gf.get(_G_INPUT, [])
            if _value_info_name(b) not in self.initializers]
        self.outputs = [_value_info_name(b) for b in gf.get(_G_OUTPUT, [])]


# ----------------------------------------------------------------- ops

_ONNX_OPS: Dict[str, Callable] = {}


def _onnx_op(*names):
    def deco(fn):
        for n in names:
            _ONNX_OPS[n] = fn
        return fn
    return deco


_onnx_op("Identity")(lambda node, x: x)
_onnx_op("Add")(lambda node, a, b: a + b)
_onnx_op("Sub")(lambda node, a, b: a - b)
_onnx_op("Mul")(lambda node, a, b: a * b)
_onnx_op("Div")(lambda node, a, b: a / b)
_onnx_op("Pow")(lambda node, a, b: jnp.power(a, b))
_onnx_op("Sqrt")(lambda node, x: jnp.sqrt(x))
_onnx_op("Exp")(lambda node, x: jnp.exp(x))
_onnx_op("Log")(lambda node, x: jnp.log(x))
_onnx_op("Neg")(lambda node, x: -x)
_onnx_op("Abs")(lambda node, x: jnp.abs(x))
_onnx_op("Erf")(lambda node, x: lax.erf(x))
_onnx_op("Relu")(lambda node, x: jax.nn.relu(x))
_onnx_op("Sigmoid")(lambda node, x: jax.nn.sigmoid(x))
_onnx_op("Tanh")(lambda node, x: jnp.tanh(x))
_onnx_op("Where")(lambda node, c, a, b: jnp.where(c, a, b))
_onnx_op("Equal")(lambda node, a, b: a == b)
_onnx_op("Greater")(lambda node, a, b: a > b)
_onnx_op("Less")(lambda node, a, b: a < b)
_onnx_op("MatMul")(lambda node, a, b: jnp.matmul(a, b))
_onnx_op("Reciprocal")(lambda node, x: 1.0 / x)


@_onnx_op("LeakyRelu")
def _leaky(node, x):
    return jax.nn.leaky_relu(x, node.attrs.get("alpha", 0.01))


@_onnx_op("Elu")
def _elu(node, x):
    return jax.nn.elu(x, node.attrs.get("alpha", 1.0))


@_onnx_op("Softmax")
def _softmax(node, x):
    return jax.nn.softmax(x, axis=node.attrs.get("axis", -1))


@_onnx_op("LogSoftmax")
def _log_softmax(node, x):
    return jax.nn.log_softmax(x, axis=node.attrs.get("axis", -1))


@_onnx_op("Gemm")
def _gemm(node, a, b, c=None):
    alpha = node.attrs.get("alpha", 1.0)
    beta = node.attrs.get("beta", 1.0)
    if node.attrs.get("transA", 0):
        a = a.T
    if node.attrs.get("transB", 0):
        b = b.T
    out = alpha * (a @ b)
    if c is not None:
        out = out + beta * c
    return out


@_onnx_op("Conv")
def _conv(node, x, w, b=None):
    strides = tuple(node.attrs.get("strides", [1] * (x.ndim - 2)))
    pads = node.attrs.get("pads")
    group = node.attrs.get("group", 1)
    dil = tuple(node.attrs.get("dilations", [1] * (x.ndim - 2)))
    nd = x.ndim - 2
    if node.attrs.get("auto_pad", "NOTSET") in ("SAME_UPPER", "SAME_LOWER"):
        padding = "SAME"
    elif pads:
        padding = tuple((pads[i], pads[i + nd]) for i in range(nd))
    else:
        padding = "VALID"
    sp = "DHW"[-nd:]
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding, rhs_dilation=dil,
        feature_group_count=group,
        dimension_numbers=(f"NC{sp}", f"OI{sp}", f"NC{sp}"))
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * nd)
    return out


@_onnx_op("MaxPool")
def _max_pool(node, x):
    k = tuple(node.attrs["kernel_shape"])
    s = tuple(node.attrs.get("strides", k))
    pads = node.attrs.get("pads", [0] * (2 * len(k)))
    nd = len(k)
    pad_cfg = ((0, 0), (0, 0)) + tuple(
        (pads[i], pads[i + nd]) for i in range(nd))
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 1) + k, (1, 1) + s,
                             pad_cfg)


@_onnx_op("AveragePool")
def _avg_pool(node, x):
    k = tuple(node.attrs["kernel_shape"])
    s = tuple(node.attrs.get("strides", k))
    pads = node.attrs.get("pads", [0] * (2 * len(k)))
    nd = len(k)
    pad_cfg = ((0, 0), (0, 0)) + tuple(
        (pads[i], pads[i + nd]) for i in range(nd))
    summed = lax.reduce_window(x, 0.0, lax.add, (1, 1) + k, (1, 1) + s,
                               pad_cfg)
    include_pad = bool(node.attrs.get("count_include_pad", 0))
    if include_pad or not any(pads):
        return summed / np.prod(k)
    # ONNX default: average over VALID cells only at padded borders
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                               (1, 1) + k, (1, 1) + s, pad_cfg)
    return summed / counts


@_onnx_op("GlobalAveragePool")
def _gap(node, x):
    return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@_onnx_op("BatchNormalization")
def _bn(node, x, gamma, beta, mean, var):
    eps = node.attrs.get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return ((x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + eps)
            * gamma.reshape(shape) + beta.reshape(shape))


@_onnx_op("LayerNormalization")
def _ln(node, x, gamma, beta=None):
    axis = node.attrs.get("axis", -1)
    eps = node.attrs.get("epsilon", 1e-5)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps) * gamma
    return out + beta if beta is not None else out


@_onnx_op("Flatten")
def _flatten(node, x):
    axis = node.attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return jnp.reshape(x, (lead, -1))


@_onnx_op("Reshape")
def _reshape(node, x, shape):
    tgt = [int(s) for s in np.asarray(shape).reshape(-1)]
    tgt = [x.shape[i] if s == 0 else s for i, s in enumerate(tgt)]
    return jnp.reshape(x, tgt)


@_onnx_op("Transpose")
def _transpose(node, x):
    perm = node.attrs.get("perm")
    return jnp.transpose(x, perm)


@_onnx_op("Concat")
def _concat(node, *args):
    return jnp.concatenate(args, axis=node.attrs.get("axis", 0))


@_onnx_op("Unsqueeze")
def _unsqueeze(node, x, axes=None):
    ax = axes if axes is not None else node.attrs.get("axes")
    for a in sorted(int(v) for v in np.asarray(ax).reshape(-1)):
        x = jnp.expand_dims(x, a)
    return x


@_onnx_op("Squeeze")
def _squeeze(node, x, axes=None):
    ax = axes if axes is not None else node.attrs.get("axes")
    if ax is None:
        return jnp.squeeze(x)
    return jnp.squeeze(x, tuple(int(v) for v in np.asarray(ax).reshape(-1)))


@_onnx_op("Gather")
def _gather(node, data, indices):
    axis = node.attrs.get("axis", 0)
    return jnp.take(data, jnp.asarray(indices).astype(jnp.int32), axis=axis)


@_onnx_op("ReduceMean")
def _reduce_mean(node, x, axes=None):
    ax = axes if axes is not None else node.attrs.get("axes")
    keep = bool(node.attrs.get("keepdims", 1))
    ax = tuple(int(v) for v in np.asarray(ax).reshape(-1)) \
        if ax is not None else None
    return jnp.mean(x, axis=ax, keepdims=keep)


@_onnx_op("Clip")
def _clip(node, x, lo=None, hi=None):
    lo = node.attrs.get("min", lo)
    hi = node.attrs.get("max", hi)
    return jnp.clip(x, None if lo is None else np.asarray(lo),
                    None if hi is None else np.asarray(hi))


@_onnx_op("Dropout")
def _dropout(node, x, *rest):
    return x  # inference semantics


@_onnx_op("Cast")
def _cast(node, x):
    dt = _DTYPES[int(node.attrs["to"])]
    if dt == np.int64:
        dt = np.int32
    elif dt == np.float64:
        dt = np.float32
    return jnp.asarray(x).astype(dt)


@_onnx_op("Constant")
def _constant(node):
    return node.attrs.get("value")


@_onnx_op("Shape")
def _shape(node, x):
    return np.asarray(x.shape, np.int32)


@_onnx_op("Slice")
def _slice(node, x, starts=None, ends=None, axes=None, steps=None):
    starts = node.attrs.get("starts", starts)
    ends = node.attrs.get("ends", ends)
    axes = node.attrs.get("axes", axes)
    steps = steps if steps is not None else [1] * len(np.asarray(starts))
    starts = [int(v) for v in np.asarray(starts).reshape(-1)]
    ends = [int(v) for v in np.asarray(ends).reshape(-1)]
    steps = [int(v) for v in np.asarray(steps).reshape(-1)]
    axes = [int(v) for v in np.asarray(axes).reshape(-1)] \
        if axes is not None else list(range(len(starts)))
    ix = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, steps):
        e = min(e, x.shape[a]) if e < (1 << 31) else x.shape[a]
        ix[a] = slice(s, e, st)
    return x[tuple(ix)]


# ------------------------------------------------------------- adapter

class OnnxGraphNet(KerasNet):
    """An ONNX graph as a trainable KerasNet: initializers are the params
    (float initializers trainable, integer ones ride in ``stats``)."""

    def __init__(self, graph: OnnxGraph, name: Optional[str] = None):
        super().__init__(name=name or "onnx")
        self.graph = graph
        w = {k: jnp.asarray(v) for k, v in graph.initializers.items()
             if np.issubdtype(np.asarray(v).dtype, np.floating)}
        consts = {k: jnp.asarray(v) for k, v in graph.initializers.items()
                  if not np.issubdtype(np.asarray(v).dtype, np.floating)}
        self.params = {"onnx": {"w": w, "stats": consts}}
        self._built_shapes = [
            (None,) + tuple(s[1:] if s else ())
            for s in (graph.input_shapes or [None] * len(graph.inputs))]

    @property
    def layers(self):
        return []

    def _input_shapes(self):
        return self._built_shapes

    def _init_params(self, rng, input_shapes):
        return self.params

    def _forward(self, params, inputs, *, training, rng, collect):
        g = params["onnx"]
        env: Dict[str, Any] = {}
        env.update(g.get("stats", {}))
        env.update(g["w"])
        for name, val in zip(self.graph.inputs, inputs):
            env[name] = val
        for node in self.graph.nodes:
            fn = _ONNX_OPS.get(node.op)
            if fn is None:
                raise NotImplementedError(
                    f"ONNX op {node.op} (node {node.name!r}) has no JAX "
                    "mapping in zoo_tpu.pipeline.api.onnx")
            args = [env[i] if i else None for i in node.inputs]
            out = fn(node, *args)
            if len(node.outputs) == 1:
                env[node.outputs[0]] = out
            else:
                for oname, oval in zip(node.outputs,
                                       out if isinstance(out, tuple)
                                       else (out,)):
                    env[oname] = oval
        outs = [env[o] for o in self.graph.outputs]
        return outs[0] if len(outs) == 1 else tuple(outs)


def load_onnx(path_or_bytes) -> OnnxGraphNet:
    """Load an ONNX file into a trainable zoo model (reference:
    ``OnnxLoader.load_model``)."""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    return OnnxGraphNet(OnnxGraph(data))
