"""Reference ``zoo.pipeline.api.torch`` compat
(``pyzoo/zoo/pipeline/api/torch/torch_model.py:36`` ``TorchModel``,
``torch_criterion.py`` ``TorchLoss``, ``torch_optim.py`` ``TorchOptim``
— the jep path shipping pickled torch modules into executor JVMs).

The rebuild ingests torch natively through ``torch.export`` tracing
(``bridges/fx_bridge.py``): ``TorchModel.from_pytorch`` returns a zoo
model that trains/predicts on TPU, ``TorchLoss.from_pytorch`` wraps a
torch loss callable for the Orca torch estimator, and ``TorchOptim``
maps torch optimizer configs onto the keras-facade optimizers.
"""

from __future__ import annotations


class TorchModel:
    """reference ``torch_model.py:36``."""

    @staticmethod
    def from_pytorch(module, example_inputs=None, input_shape=None):
        """Trace a torch ``nn.Module`` into a TPU-trainable zoo model.
        Provide ``example_inputs`` (preferred) or an ``input_shape``
        from which a float example is synthesized."""
        import torch

        from zoo_tpu.pipeline.api.net import Net

        if example_inputs is None:
            if input_shape is None:
                raise ValueError(
                    "from_pytorch needs example_inputs=[tensor,...] or "
                    "input_shape=(...) to trace the module")
            example_inputs = [torch.randn(*input_shape)]
        return Net.load_torch(module, example_inputs)


class TorchLoss:
    """reference ``torch_criterion.py`` — wraps a torch loss for the
    Orca torch estimator (which consumes torch callables directly)."""

    @staticmethod
    def from_pytorch(criterion):
        return criterion


class TorchOptim:
    """reference ``torch_optim.py`` — torch optimizer spec → the
    keras-facade optimizer the traced model trains with."""

    @staticmethod
    def from_pytorch(optimizer):
        import torch

        from zoo_tpu.pipeline.api.keras import optimizers as zopt

        lr = optimizer.param_groups[0].get("lr", 1e-3) \
            if hasattr(optimizer, "param_groups") else 1e-3
        if isinstance(optimizer, torch.optim.SGD):
            mom = optimizer.param_groups[0].get("momentum", 0.0)
            return zopt.SGD(lr=lr, momentum=mom)
        if isinstance(optimizer, torch.optim.AdamW):
            wd = optimizer.param_groups[0].get("weight_decay", 0.01)
            return zopt.AdamWeightDecay(lr=lr, weight_decay=wd)
        if isinstance(optimizer, torch.optim.RMSprop):
            return zopt.RMSprop(lr=lr)
        return zopt.Adam(lr=lr)
