"""Optimizer facade over optax with keras-1 names/defaults.

Reference: Python wrappers ``pyzoo/zoo/orca/learn/optimizers/`` +
``pipeline/api/keras/optimizers.py`` (Adam with schedule support,
AdamWeightDecay / LARS-style, ``PolyEpochDecay``), Scala
``keras/optimizers/``. The reference applied these slice-wise inside the
parameter-server update (``Topology.scala:1204``); here the whole update is
one fused XLA computation — the reference's "apply update on the aggregated
slice" is the optimizer update after psum'd grads, which XLA schedules as
reduce-scatter + apply + all-gather automatically when params are sharded.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Union

import optax


class Optimizer:
    """Thin wrapper producing an optax GradientTransformation.

    ``plateau`` is set when the user passed a metric-driven
    :class:`~zoo_tpu.orca.learn.optimizers.schedule.Plateau` schedule: the
    transformation is then built with ``optax.inject_hyperparams`` so the
    training loop can write the reduced lr into the optimizer state between
    epochs (the reference's JVM Plateau mutates the optim method's ``clr``
    the same way, driver-side)."""

    #: True when the optimizer provides the direct-apply path
    #: (init_fused/apply_fused) backed by a Pallas fused kernel
    fused = False

    def __init__(self, tx: optax.GradientTransformation, name: str,
                 plateau=None):
        self.tx = tx
        self.name = name
        self.plateau = plateau

    def make(self) -> optax.GradientTransformation:
        return self.tx


def _schedule(lr: float, decay: float) -> Union[float, Callable]:
    """keras-1 `decay`: lr / (1 + decay * iterations)."""
    if not decay:
        return lr
    return lambda step: lr / (1.0 + decay * step)


def _resolve(factory, lr, keras_decay, learningrate_schedule, **kw):
    """Compile (base lr, keras decay, schedule object) into a
    GradientTransformation + optional Plateau controller.

    Accepts a Scheduler from ``zoo_tpu.orca.learn.optimizers.schedule``
    (reference ``orca/learn/optimizers/schedule.py``), a raw ``step -> lr``
    callable, or nothing (keras-1 inverse-time ``decay``)."""
    from zoo_tpu.orca.learn.optimizers.schedule import Plateau, Scheduler

    sched = learningrate_schedule
    if isinstance(sched, Plateau):
        return optax.inject_hyperparams(factory)(
            learning_rate=lr, **kw), sched.bind(lr)
    if isinstance(sched, Scheduler):
        return factory(sched.get_scheduler(lr), **kw), None
    if callable(sched):
        return factory(sched, **kw), None
    return factory(_schedule(lr, keras_decay), **kw), None


class SGD(Optimizer):
    def __init__(self, lr: float = 0.01, momentum: float = 0.0,
                 decay: float = 0.0, nesterov: bool = False,
                 learningrate_schedule=None):
        tx, plateau = _resolve(optax.sgd, lr, decay, learningrate_schedule,
                               momentum=momentum or None, nesterov=nesterov)
        super().__init__(tx, "sgd", plateau)


class Adam(Optimizer):
    def __init__(self, lr: float = 0.001, beta_1: float = 0.9,  # zoo-lint: config-parse
                 beta_2: float = 0.999, epsilon: float = 1e-8,
                 decay: float = 0.0, learningrate_schedule=None):
        tx, plateau = _resolve(optax.adam, lr, decay, learningrate_schedule,
                               b1=beta_1, b2=beta_2, eps=epsilon)
        super().__init__(tx, "adam", plateau)


class AdamWeightDecay(Optimizer):
    """BERT-style AdamW (reference: ``keras/optimizers.py`` AdamWeightDecay,
    used by the Scala ``BERT.scala`` training configs).

    ``fused=True`` applies the update with the Pallas fused-apply kernel
    (``ops/pallas/fused_optim.py`` — the "apply optimizer to the
    aggregated slice in-task" leg of the reference's PS allreduce,
    ``wp-bigdl.md:146-160``) through the direct-apply path of the train
    step, skipping the optax updates/apply round trip. Constant lr only
    (schedules stay on the optax path). ``fused=None`` (default) reads
    the ``ZOO_FUSED_OPTIM`` env knob — "1" turns the direct-apply path
    on deployment-wide for schedule-free configs (a scheduled config
    silently keeps the optax path rather than erroring, so one env var
    can cover a whole job). Inside a >1-device mesh the update runs as
    the partitionable elementwise form; off-TPU the kernel interprets —
    either way the fallback is clean (``bench_fused_optim`` measures the
    A/B)."""

    def __init__(self, lr: float = 0.001, beta_1: float = 0.9,  # zoo-lint: config-parse
                 beta_2: float = 0.999, epsilon: float = 1e-6,
                 weight_decay: float = 0.01, total_steps: int = 0,
                 warmup_ratio: float = 0.1, learningrate_schedule=None,
                 fused: Optional[bool] = None):
        if learningrate_schedule is None and total_steps:
            warmup = max(1, int(total_steps * warmup_ratio))
            learningrate_schedule = optax.warmup_cosine_decay_schedule(
                0.0, lr, warmup, total_steps)
        tx, plateau = _resolve(optax.adamw, lr, 0.0, learningrate_schedule,
                               b1=beta_1, b2=beta_2, eps=epsilon,
                               weight_decay=weight_decay)
        super().__init__(tx, "adamw", plateau)
        if fused and learningrate_schedule is not None:
            raise ValueError("fused=True supports a constant lr only")
        if fused is None:
            fused = (os.environ.get("ZOO_FUSED_OPTIM", "").lower()
                     in ("1", "true")
                     and learningrate_schedule is None)
        if fused:
            self.fused = True
            self._fused_args = (float(lr), float(beta_1), float(beta_2),
                                float(epsilon), float(weight_decay))

    def init_fused(self, trainable):
        import jax
        import jax.numpy as jnp
        # zeros_like keeps the parameter's sharding, so fused moments are
        # FSDP-sharded exactly like the non-fused tx.init state
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), trainable)
        return {"m": zeros,
                "v": jax.tree_util.tree_map(jnp.copy, zeros),
                "step": jnp.zeros((), jnp.int32)}

    def apply_fused(self, grads, state, trainable):
        """Direct-apply: returns (new_trainable, new_state)."""
        import jax
        from zoo_tpu.ops.pallas.fused_optim import fused_apply_adam

        lr, b1, b2, eps, wd = self._fused_args
        step = state["step"] + 1

        def leaf(p, g, m, v):
            return fused_apply_adam(p, g, m, v, step, lr, beta1=b1,
                                    beta2=b2, eps=eps, weight_decay=wd)

        out = jax.tree_util.tree_map(leaf, trainable, grads,
                                     state["m"], state["v"])
        is_triple = lambda t: isinstance(t, tuple) and len(t) == 3
        new_p = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=is_triple)
        new_m = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=is_triple)
        new_v = jax.tree_util.tree_map(lambda t: t[2], out, is_leaf=is_triple)
        return new_p, {"m": new_m, "v": new_v, "step": step}


class RMSprop(Optimizer):
    def __init__(self, lr: float = 0.001, rho: float = 0.9,
                 epsilon: float = 1e-8, decay: float = 0.0,
                 learningrate_schedule=None):
        tx, plateau = _resolve(optax.rmsprop, lr, decay,
                               learningrate_schedule, decay=rho, eps=epsilon)
        super().__init__(tx, "rmsprop", plateau)


class Adagrad(Optimizer):
    def __init__(self, lr: float = 0.01, epsilon: float = 1e-8,
                 decay: float = 0.0, learningrate_schedule=None):
        tx, plateau = _resolve(optax.adagrad, lr, decay,
                               learningrate_schedule, eps=epsilon)
        super().__init__(tx, "adagrad", plateau)


class Adadelta(Optimizer):
    def __init__(self, lr: float = 1.0, rho: float = 0.95,
                 epsilon: float = 1e-8, decay: float = 0.0,
                 learningrate_schedule=None):
        tx, plateau = _resolve(optax.adadelta, lr, decay,
                               learningrate_schedule, rho=rho, eps=epsilon)
        super().__init__(tx, "adadelta", plateau)


class Adamax(Optimizer):
    def __init__(self, lr: float = 0.002, beta_1: float = 0.9,
                 beta_2: float = 0.999, epsilon: float = 1e-8,
                 decay: float = 0.0, learningrate_schedule=None):
        tx, plateau = _resolve(optax.adamax, lr, decay,
                               learningrate_schedule,
                               b1=beta_1, b2=beta_2, eps=epsilon)
        super().__init__(tx, "adamax", plateau)


class LARS(Optimizer):
    """Layer-wise adaptive rate scaling for large-batch training (reference
    ships a LARS-ish variant for ImageNet runs)."""

    def __init__(self, lr: float = 0.1, momentum: float = 0.9,
                 weight_decay: float = 1e-4, trust_coefficient: float = 0.001,
                 learningrate_schedule=None):
        tx, plateau = _resolve(optax.lars, lr, 0.0, learningrate_schedule,
                               weight_decay=weight_decay, momentum=momentum,
                               trust_coefficient=trust_coefficient)
        super().__init__(tx, "lars", plateau)


_ALIASES = {
    "sgd": SGD,
    "adam": Adam,
    "adamw": AdamWeightDecay,
    "rmsprop": RMSprop,
    "adagrad": Adagrad,
    "adadelta": Adadelta,
    "adamax": Adamax,
    "lars": LARS,
}


def get_optimizer(identifier) -> Optimizer:
    if isinstance(identifier, Optimizer):
        return identifier
    if isinstance(identifier, optax.GradientTransformation):
        return Optimizer(identifier, "optax")
    key = str(identifier).lower()
    if key not in _ALIASES:
        raise ValueError(f"unknown optimizer: {identifier}")
    return _ALIASES[key]()
