"""Optimizer facade over optax with keras-1 names/defaults.

Reference: Python wrappers ``pyzoo/zoo/orca/learn/optimizers/`` +
``pipeline/api/keras/optimizers.py`` (Adam with schedule support,
AdamWeightDecay / LARS-style, ``PolyEpochDecay``), Scala
``keras/optimizers/``. The reference applied these slice-wise inside the
parameter-server update (``Topology.scala:1204``); here the whole update is
one fused XLA computation — the reference's "apply update on the aggregated
slice" is the optimizer update after psum'd grads, which XLA schedules as
reduce-scatter + apply + all-gather automatically when params are sharded.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import optax


class Optimizer:
    """Thin wrapper producing an optax GradientTransformation."""

    def __init__(self, tx: optax.GradientTransformation, name: str):
        self.tx = tx
        self.name = name

    def make(self) -> optax.GradientTransformation:
        return self.tx


def _schedule(lr: float, decay: float) -> Union[float, Callable]:
    """keras-1 `decay`: lr / (1 + decay * iterations)."""
    if not decay:
        return lr
    return lambda step: lr / (1.0 + decay * step)


class SGD(Optimizer):
    def __init__(self, lr: float = 0.01, momentum: float = 0.0,
                 decay: float = 0.0, nesterov: bool = False,
                 learningrate_schedule=None):
        sched = learningrate_schedule or _schedule(lr, decay)
        tx = optax.sgd(sched, momentum=momentum or None, nesterov=nesterov)
        super().__init__(tx, "sgd")


class Adam(Optimizer):
    def __init__(self, lr: float = 0.001, beta_1: float = 0.9,
                 beta_2: float = 0.999, epsilon: float = 1e-8,
                 decay: float = 0.0, learningrate_schedule=None):
        sched = learningrate_schedule or _schedule(lr, decay)
        tx = optax.adam(sched, b1=beta_1, b2=beta_2, eps=epsilon)
        super().__init__(tx, "adam")


class AdamWeightDecay(Optimizer):
    """BERT-style AdamW (reference: ``keras/optimizers.py`` AdamWeightDecay,
    used by the Scala ``BERT.scala`` training configs)."""

    def __init__(self, lr: float = 0.001, beta_1: float = 0.9,
                 beta_2: float = 0.999, epsilon: float = 1e-6,
                 weight_decay: float = 0.01, total_steps: int = 0,
                 warmup_ratio: float = 0.1):
        if total_steps:
            warmup = max(1, int(total_steps * warmup_ratio))
            sched = optax.warmup_cosine_decay_schedule(
                0.0, lr, warmup, total_steps)
        else:
            sched = lr
        tx = optax.adamw(sched, b1=beta_1, b2=beta_2, eps=epsilon,
                         weight_decay=weight_decay)
        super().__init__(tx, "adamw")


class RMSprop(Optimizer):
    def __init__(self, lr: float = 0.001, rho: float = 0.9,
                 epsilon: float = 1e-8, decay: float = 0.0):
        tx = optax.rmsprop(_schedule(lr, decay), decay=rho, eps=epsilon)
        super().__init__(tx, "rmsprop")


class Adagrad(Optimizer):
    def __init__(self, lr: float = 0.01, epsilon: float = 1e-8,
                 decay: float = 0.0):
        tx = optax.adagrad(_schedule(lr, decay), eps=epsilon)
        super().__init__(tx, "adagrad")


class Adadelta(Optimizer):
    def __init__(self, lr: float = 1.0, rho: float = 0.95,
                 epsilon: float = 1e-8):
        tx = optax.adadelta(lr, rho=rho, eps=epsilon)
        super().__init__(tx, "adadelta")


class Adamax(Optimizer):
    def __init__(self, lr: float = 0.002, beta_1: float = 0.9,
                 beta_2: float = 0.999, epsilon: float = 1e-8):
        tx = optax.adamax(lr, b1=beta_1, b2=beta_2, eps=epsilon)
        super().__init__(tx, "adamax")


class LARS(Optimizer):
    """Layer-wise adaptive rate scaling for large-batch training (reference
    ships a LARS-ish variant for ImageNet runs)."""

    def __init__(self, lr: float = 0.1, momentum: float = 0.9,
                 weight_decay: float = 1e-4, trust_coefficient: float = 0.001):
        tx = optax.lars(lr, weight_decay=weight_decay,
                        momentum=momentum,
                        trust_coefficient=trust_coefficient)
        super().__init__(tx, "lars")


_ALIASES = {
    "sgd": SGD,
    "adam": Adam,
    "adamw": AdamWeightDecay,
    "rmsprop": RMSprop,
    "adagrad": Adagrad,
    "adadelta": Adadelta,
    "adamax": Adamax,
    "lars": LARS,
}


def get_optimizer(identifier) -> Optimizer:
    if isinstance(identifier, Optimizer):
        return identifier
    if isinstance(identifier, optax.GradientTransformation):
        return Optimizer(identifier, "optax")
    key = str(identifier).lower()
    if key not in _ALIASES:
        raise ValueError(f"unknown optimizer: {identifier}")
    return _ALIASES[key]()
