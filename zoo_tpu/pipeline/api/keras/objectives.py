"""Loss functions, keras-1 names (reference: Python
``pyzoo/zoo/pipeline/api/keras/objectives.py``, Scala
``pipeline/api/keras/objectives/``). All pure jittable ``f(y_true, y_pred)
-> scalar`` reducing with mean over all elements, matching keras-1.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

_EPS = 1e-7


def mean_squared_error(y_true, y_pred):
    return jnp.mean((y_pred - y_true) ** 2)


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(y_pred - y_true))


def mean_absolute_percentage_error(y_true, y_pred):
    diff = jnp.abs((y_true - y_pred) / jnp.maximum(jnp.abs(y_true), _EPS))
    return 100.0 * jnp.mean(diff)


def mean_squared_logarithmic_error(y_true, y_pred):
    a = jnp.log(jnp.maximum(y_pred, _EPS) + 1.0)
    b = jnp.log(jnp.maximum(y_true, _EPS) + 1.0)
    return jnp.mean((a - b) ** 2)


def binary_crossentropy(y_true, y_pred):
    """y_pred are probabilities (keras-1 contract; the reference's
    ``BinaryCrossEntropy``)."""
    p = jnp.clip(y_pred, _EPS, 1.0 - _EPS)
    return -jnp.mean(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log(1.0 - p))


def binary_crossentropy_from_logits(y_true, logits):
    return jnp.mean(jnp.maximum(logits, 0) - logits * y_true +
                    jnp.log1p(jnp.exp(-jnp.abs(logits))))


def categorical_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred, _EPS, 1.0)
    return -jnp.mean(jnp.sum(y_true * jnp.log(p), axis=-1))


def sparse_categorical_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred, _EPS, 1.0)
    idx = y_true.astype(jnp.int32)
    if idx.ndim == p.ndim:  # (batch, 1) labels
        idx = idx.squeeze(-1)
    picked = jnp.take_along_axis(jnp.log(p), idx[..., None], axis=-1)
    return -jnp.mean(picked)


def _class_last(y_true, t):
    """Normalize to class-axis-last. The class axis may be last (keras
    layout) or dim 1 (torch's (N, C, ...) layout for >2D inputs); detected
    from the label shape, preferring the keras layout when ambiguous."""
    idx = y_true.astype(jnp.int32)
    if idx.ndim == t.ndim:  # (N, ..., 1)-shaped labels
        idx = idx.squeeze(-1)
    if t.ndim > 2 and idx.shape != t.shape[:-1] \
            and idx.shape == (t.shape[0],) + t.shape[2:]:
        t = jnp.moveaxis(t, 1, -1)
    return idx, t


def _sparse_nll(idx, logp, ignore_index: int = -100):
    """NLL over class-last log-probs; labels equal to ``ignore_index``
    (torch's -100 padding convention) are masked out of the mean."""
    mask = idx != ignore_index
    safe = jnp.where(mask, idx, 0)
    picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    total = jnp.sum(jnp.where(mask, -picked, 0.0))
    return total / jnp.maximum(jnp.sum(mask), 1)


def sparse_categorical_crossentropy_from_logits(y_true, logits):
    """torch ``nn.CrossEntropyLoss`` semantics (logits in, int labels;
    channel-first layouts and ``ignore_index=-100`` respected).

    Written as logsumexp-minus-gather rather than a full ``log_softmax``
    so only the two reduced tensors are produced in f32 — with a large
    vocab the (B, T, V) f32 log-probs tensor would dominate peak HBM
    (4.2GB at B=64, T=512, V=32k). Accepts bf16 logits directly (marked
    ``_handles_low_precision``: the train step skips its blanket f32
    upcast); the reductions and the final arithmetic run in f32."""
    idx, logits = _class_last(y_true, logits)
    mask = idx != -100
    safe = jnp.where(mask, idx, 0)
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logits, safe[..., None], axis=-1)[..., 0].astype(jnp.float32)
    total = jnp.sum(jnp.where(mask, lse - picked, 0.0))
    return total / jnp.maximum(jnp.sum(mask), 1)


sparse_categorical_crossentropy_from_logits._handles_low_precision = True


def nll_loss(y_true, log_probs):
    """torch ``nn.NLLLoss`` semantics (log-probabilities in)."""
    idx, logp = _class_last(y_true, log_probs)
    return _sparse_nll(idx, logp)


def hinge(y_true, y_pred):
    return jnp.mean(jnp.maximum(1.0 - y_true * y_pred, 0.0))


def squared_hinge(y_true, y_pred):
    return jnp.mean(jnp.maximum(1.0 - y_true * y_pred, 0.0) ** 2)


def kullback_leibler_divergence(y_true, y_pred):
    p = jnp.clip(y_true, _EPS, 1.0)
    q = jnp.clip(y_pred, _EPS, 1.0)
    return jnp.mean(jnp.sum(p * jnp.log(p / q), axis=-1))


def poisson(y_true, y_pred):
    return jnp.mean(y_pred - y_true * jnp.log(jnp.maximum(y_pred, _EPS)))


def cosine_proximity(y_true, y_pred):
    a = y_true / jnp.maximum(jnp.linalg.norm(y_true, axis=-1, keepdims=True), _EPS)
    b = y_pred / jnp.maximum(jnp.linalg.norm(y_pred, axis=-1, keepdims=True), _EPS)
    return -jnp.mean(jnp.sum(a * b, axis=-1))


_ALIASES = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "binary_crossentropy": binary_crossentropy,
    "bce": binary_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_from_logits":
        sparse_categorical_crossentropy_from_logits,
    "binary_crossentropy_from_logits": binary_crossentropy_from_logits,
    "nll": nll_loss,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
}


def get_loss(identifier: Union[str, Callable]) -> Callable:
    if callable(identifier):
        return identifier
    key = identifier.lower()
    if key not in _ALIASES:
        raise ValueError(f"unknown loss: {identifier}")
    return _ALIASES[key]


class LossFunction:
    """reference class-style objective base (``objectives.py``): the
    class names below instantiate to the plain loss callables above —
    ``compile(loss=SparseCategoricalCrossEntropy())`` works like
    ``compile(loss="sparse_categorical_crossentropy")``."""

    _fn = None

    def __new__(cls, *args, **kwargs):
        if cls._fn is None:
            raise TypeError("LossFunction is abstract")
        return cls._fn


def _loss_class(name, fn):
    return type(name, (LossFunction,), {"_fn": staticmethod(fn),
                                        "__doc__": fn.__doc__})


SparseCategoricalCrossEntropy = _loss_class(
    "SparseCategoricalCrossEntropy", sparse_categorical_crossentropy)
CategoricalCrossEntropy = _loss_class(
    "CategoricalCrossEntropy", categorical_crossentropy)
BinaryCrossEntropy = _loss_class("BinaryCrossEntropy",
                                 binary_crossentropy)
MeanSquaredError = _loss_class("MeanSquaredError", mean_squared_error)
MeanAbsoluteError = _loss_class("MeanAbsoluteError", mean_absolute_error)
Hinge = _loss_class("Hinge", hinge)
SquaredHinge = _loss_class("SquaredHinge", squared_hinge)
KullbackLeiblerDivergence = _loss_class("KullbackLeiblerDivergence",
                                        kullback_leibler_divergence)
Poisson = _loss_class("Poisson", poisson)
CosineProximity = _loss_class("CosineProximity", cosine_proximity)
