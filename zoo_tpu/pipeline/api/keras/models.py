"""Keras models namespace (reference:
``pyzoo/zoo/pipeline/api/keras/models.py`` — exposes Sequential/Model).
The engine lives in ``engine.topology``; this module is the reference's
import path for it."""

from zoo_tpu.pipeline.api.keras.engine.topology import (  # noqa: F401
    Input,
    KerasNet,
    Model,
    Sequential,
)

__all__ = ["Input", "KerasNet", "Model", "Sequential"]
