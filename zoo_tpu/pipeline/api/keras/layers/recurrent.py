"""Recurrent layers via ``lax.scan``, keras-1 style.

Rebuild of the reference's recurrent set (Python
``pyzoo/zoo/pipeline/api/keras/layers/recurrent.py``, Scala ``LSTM.scala`` /
``GRU.scala`` / ``SimpleRNN.scala``; keras-1 gate conventions).

TPU note: the recurrence is a ``jax.lax.scan`` over time — one compiled
loop body, no Python unrolling, so long sequences compile in O(1) and the
per-step matmuls (batch × 4·hidden) land on the MXU. The input projection
``x @ W`` for ALL timesteps is hoisted out of the scan into one big
(B·T, in)×(in, 4H) matmul — much better MXU utilization than per-step.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import (
    Layer,
    get_activation_fn,
    get_initializer,
)


class _Recurrent(Layer):
    gate_mult = 1

    def __init__(self, output_dim: int, init="glorot_uniform",
                 inner_init="orthogonal", activation="tanh",
                 inner_activation="hard_sigmoid",
                 return_sequences: bool = False, go_backwards: bool = False,
                 **kwargs):
        super().__init__(**kwargs)
        self.output_dim = int(output_dim)
        self.init = get_initializer(init)
        self.inner_init = get_initializer(inner_init)
        self.activation = get_activation_fn(activation)
        self.inner_activation = get_activation_fn(inner_activation)
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    def build(self, rng, input_shape):
        in_dim = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        g = self.gate_mult
        return {
            "W": self.init(k1, (in_dim, g * self.output_dim), jnp.float32),
            "U": self.inner_init(k2, (self.output_dim, g * self.output_dim),
                                 jnp.float32),
            "b": jnp.zeros((g * self.output_dim,), jnp.float32),
        }

    def _init_carry(self, batch):
        raise NotImplementedError

    def _step(self, params, carry, zx):
        """One timestep; ``zx`` is the precomputed input projection."""
        raise NotImplementedError

    def call(self, params, inputs, *, training=False, rng=None):
        # (B, T, D) -> precompute input projection for all steps at once
        zx_all = jnp.einsum("btd,dh->bth", inputs, params["W"]) + params["b"]
        zx_tm = jnp.swapaxes(zx_all, 0, 1)  # time-major (T, B, gH)
        if self.go_backwards:
            zx_tm = zx_tm[::-1]
        carry0 = self._init_carry(inputs.shape[0])

        def body(carry, zx):
            carry, h = self._step(params, carry, zx)
            return carry, h

        _, hs = jax.lax.scan(body, carry0, zx_tm)
        if self.return_sequences:
            hs = jnp.swapaxes(hs, 0, 1)
            return hs[:, ::-1] if self.go_backwards else hs
        return hs[-1]

    def compute_output_shape(self, input_shape):
        n, t, _ = input_shape
        if self.return_sequences:
            return (n, t, self.output_dim)
        return (n, self.output_dim)


class SimpleRNN(_Recurrent):
    gate_mult = 1

    def _init_carry(self, batch):
        return jnp.zeros((batch, self.output_dim))

    def _step(self, params, h, zx):
        h = self.activation(zx + h @ params["U"])
        return h, h


class LSTM(_Recurrent):
    """keras-1 gate order i, f, c, o (reference: Scala ``LSTM.scala``)."""

    gate_mult = 4

    def _init_carry(self, batch):
        return (jnp.zeros((batch, self.output_dim)),
                jnp.zeros((batch, self.output_dim)))

    def _step(self, params, carry, zx):
        h, c = carry
        z = zx + h @ params["U"]
        d = self.output_dim
        i = self.inner_activation(z[:, :d])
        f = self.inner_activation(z[:, d:2 * d])
        g = self.activation(z[:, 2 * d:3 * d])
        o = self.inner_activation(z[:, 3 * d:])
        c = f * c + i * g
        h = o * self.activation(c)
        return (h, c), h


class GRU(_Recurrent):
    """keras-1 gate order z, r, h (reference: Scala ``GRU.scala``)."""

    gate_mult = 3

    def _init_carry(self, batch):
        return jnp.zeros((batch, self.output_dim))

    def _step(self, params, h, zx):
        d = self.output_dim
        U = params["U"]
        z = self.inner_activation(zx[:, :d] + h @ U[:, :d])
        r = self.inner_activation(zx[:, d:2 * d] + h @ U[:, d:2 * d])
        hh = self.activation(zx[:, 2 * d:] + (r * h) @ U[:, 2 * d:])
        h = z * h + (1 - z) * hh
        return h, h


class Bidirectional(Layer):
    """Run a recurrent layer forward and backward, merging outputs
    (reference: ``Bidirectional`` wrapper; merge modes concat/sum/mul/ave).
    """

    def __init__(self, layer: _Recurrent, merge_mode: str = "concat",
                 **kwargs):
        super().__init__(**kwargs)
        if not isinstance(layer, _Recurrent):
            raise ValueError("Bidirectional wraps a recurrent layer")
        self.forward = layer
        import copy
        self.backward = copy.copy(layer)
        self.backward.go_backwards = not layer.go_backwards
        self.merge_mode = merge_mode

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        return {"fw": self.forward.build(k1, input_shape),
                "bw": self.backward.build(k2, input_shape)}

    def call(self, params, inputs, *, training=False, rng=None):
        a = self.forward.call(params["fw"], inputs, training=training, rng=rng)
        b = self.backward.call(params["bw"], inputs, training=training,
                               rng=rng)
        if self.merge_mode == "concat":
            return jnp.concatenate([a, b], axis=-1)
        if self.merge_mode == "sum":
            return a + b
        if self.merge_mode == "mul":
            return a * b
        if self.merge_mode == "ave":
            return (a + b) / 2
        raise ValueError(f"unknown merge_mode: {self.merge_mode}")

    def compute_output_shape(self, input_shape):
        s = self.forward.compute_output_shape(input_shape)
        if self.merge_mode == "concat":
            return s[:-1] + (s[-1] * 2,)
        return s


class TimeDistributed(Layer):
    """Apply an inner layer to every timestep (reference:
    ``TimeDistributed``): fold time into batch, call once, unfold — one big
    MXU matmul instead of T small ones."""

    def __init__(self, layer: Layer, **kwargs):
        super().__init__(**kwargs)
        self.inner = layer

    def build(self, rng, input_shape):
        inner_shape = (input_shape[0],) + tuple(input_shape[2:])
        return self.inner.build(rng, inner_shape)

    def call(self, params, inputs, *, training=False, rng=None):
        b, t = inputs.shape[0], inputs.shape[1]
        flat = inputs.reshape((b * t,) + inputs.shape[2:])
        y = self.inner.call(params, flat, training=training, rng=rng)
        return y.reshape((b, t) + y.shape[1:])

    def compute_output_shape(self, input_shape):
        inner_in = (input_shape[0],) + tuple(input_shape[2:])
        inner_out = self.inner.compute_output_shape(inner_in)
        return (input_shape[0], input_shape[1]) + tuple(inner_out[1:])
