"""Advanced activations + misc dense variants, keras-1 style.

Rebuild of the reference's ``advanced_activations`` + rarities the SURVEY
calls out as fidelity-sensitive (§7.4 #2): SReLU, MaxoutDense, Highway
(Python ``keras/layers/advanced_activations.py``, Scala ``SReLU.scala``,
``MaxoutDense.scala``, ``Highway.scala``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import (
    Layer,
    get_activation_fn,
    get_initializer,
)


class LeakyReLU(Layer):
    def __init__(self, alpha: float = 0.3, **kwargs):
        super().__init__(**kwargs)
        self.alpha = float(alpha)

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.where(inputs >= 0, inputs, self.alpha * inputs)


class ELU(Layer):
    def __init__(self, alpha: float = 1.0, **kwargs):
        super().__init__(**kwargs)
        self.alpha = float(alpha)

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.where(inputs >= 0, inputs,
                         self.alpha * (jnp.exp(inputs) - 1.0))


class ThresholdedReLU(Layer):
    def __init__(self, theta: float = 1.0, **kwargs):
        super().__init__(**kwargs)
        self.theta = float(theta)

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.where(inputs > self.theta, inputs, 0.0)


class PReLU(Layer):
    """Per-feature trainable leak (reference: ``PReLU.scala``)."""

    def build(self, rng, input_shape):
        return {"alpha": jnp.full(tuple(input_shape[1:]), 0.25, jnp.float32)}

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.where(inputs >= 0, inputs, params["alpha"] * inputs)


class SReLU(Layer):
    """S-shaped ReLU with 4 trainable per-feature params t_l, a_l, t_r, a_r
    (reference: Scala ``SReLU.scala``; keras-1 defaults)."""

    def build(self, rng, input_shape):
        shape = tuple(input_shape[1:])
        return {
            "t_left": jnp.zeros(shape, jnp.float32),
            "a_left": jnp.zeros(shape, jnp.float32),
            "t_right": self.init_t_right(rng, shape),
            "a_right": jnp.ones(shape, jnp.float32),
        }

    @staticmethod
    def init_t_right(rng, shape):
        return jax.random.uniform(rng, shape, jnp.float32, 0.0, 1.0)

    def call(self, params, inputs, *, training=False, rng=None):
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y = jnp.where(inputs <= tl, tl + al * (inputs - tl), inputs)
        return jnp.where(inputs >= tr, tr + ar * (inputs - tr), y)


class Highway(Layer):
    """y = T(x) * H(x) + (1 - T(x)) * x (reference: ``Highway.scala``)."""

    def __init__(self, activation=None, bias: bool = True,
                 init="glorot_uniform", **kwargs):
        super().__init__(**kwargs)
        self.activation = get_activation_fn(activation) or (lambda x: x)
        self.bias = bias
        self.init = get_initializer(init)

    def build(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        p = {"W": self.init(k1, (d, d), jnp.float32),
             "W_carry": self.init(k2, (d, d), jnp.float32)}
        if self.bias:
            p["b"] = jnp.zeros((d,), jnp.float32)
            # negative carry bias -> pass-through at init (keras-1 default -2)
            p["b_carry"] = jnp.full((d,), -2.0, jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        h = inputs @ params["W"]
        t = inputs @ params["W_carry"]
        if self.bias:
            h = h + params["b"]
            t = t + params["b_carry"]
        h = self.activation(h)
        t = jax.nn.sigmoid(t)
        return t * h + (1 - t) * inputs


class MaxoutDense(Layer):
    """max over ``nb_feature`` linear maps (reference: ``MaxoutDense.scala``).
    """

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 init="glorot_uniform", bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.output_dim = int(output_dim)
        self.nb_feature = int(nb_feature)
        self.init = get_initializer(init)
        self.bias = bias

    def build(self, rng, input_shape):
        d = input_shape[-1]
        p = {"W": self.init(rng, (self.nb_feature, d, self.output_dim),
                            jnp.float32)}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_feature, self.output_dim), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        y = jnp.einsum("bd,kdo->bko", inputs, params["W"])
        if self.bias:
            y = y + params["b"]
        return jnp.max(y, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.output_dim)
