"""Transformer / BERT mega-layers, keras-1 style.

Rebuild of the reference's only attention models (Python
``pyzoo/zoo/pipeline/api/keras/layers/self_attention.py:46`` TransformerLayer
and ``:235`` BERT; Scala ``TransformerLayer.scala:279``, ``BERT.scala:402``).
As in the reference these are single Layer objects owning the whole stack
(embeddings + N blocks), not functional graphs.

TPU design: the block stack runs under ``jax.lax.scan`` over stacked
per-block params — one compiled block body regardless of depth (compile time
O(1) in n_block), with weights laid out (n_block, ...) which is also the
natural stacking for pipeline parallelism later. All matmuls are (B·T, H)
GEMMs on the MXU; attention math lives in ``zoo_tpu.ops.attention``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from zoo_tpu.ops.attention import (
    dot_product_attention,
    merge_heads,
    split_heads,
)
from zoo_tpu.pipeline.api.keras.engine.base import (
    Layer,
    get_activation_fn,
    get_initializer,
    layer_rng,
)


def _layer_norm(x, gamma, beta, eps=1e-5):
    # f32 island for the STATS only (mean/var in bf16 drift badly); the
    # normalized tensor drops to the compute dtype BEFORE the affine so
    # autodiff saves a bf16 residual, not a f32 one (same treatment as
    # llama's _rms_norm — the f32 product was a 2x-sized scan carry)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    return out * gamma.astype(x.dtype) + beta.astype(x.dtype)


class LayerNorm(Layer):
    """Standalone layer-normalization layer (the reference embeds this in
    its transformer; exposed here as a reusable layer too)."""

    def __init__(self, epsilon: float = 1e-5, **kwargs):
        super().__init__(**kwargs)
        self.epsilon = epsilon

    def build(self, rng, input_shape):
        d = input_shape[-1]
        return {"gamma": jnp.ones((d,), jnp.float32),
                "beta": jnp.zeros((d,), jnp.float32)}

    def call(self, params, inputs, *, training=False, rng=None):
        return _layer_norm(inputs, params["gamma"], params["beta"],
                           self.epsilon)


def _block_params(rng, hidden: int, intermediate: int, init):
    ks = jax.random.split(rng, 6)
    return {
        "qkv_w": init(ks[0], (hidden, 3 * hidden), jnp.float32),
        "qkv_b": jnp.zeros((3 * hidden,), jnp.float32),
        "proj_w": init(ks[1], (hidden, hidden), jnp.float32),
        "proj_b": jnp.zeros((hidden,), jnp.float32),
        "ln1_g": jnp.ones((hidden,), jnp.float32),
        "ln1_b": jnp.zeros((hidden,), jnp.float32),
        "fc1_w": init(ks[2], (hidden, intermediate), jnp.float32),
        "fc1_b": jnp.zeros((intermediate,), jnp.float32),
        "fc2_w": init(ks[3], (intermediate, hidden), jnp.float32),
        "fc2_b": jnp.zeros((hidden,), jnp.float32),
        "ln2_g": jnp.ones((hidden,), jnp.float32),
        "ln2_b": jnp.zeros((hidden,), jnp.float32),
    }


def _block_forward(p, h, *, n_head, mask, causal, act, hidden_drop,
                   attn_drop, training, rng, attention_impl="auto"):
    """Post-LN transformer block (the reference's TransformerLayer/BERT use
    post-layernorm, GPT-1/BERT style)."""
    qkv = h @ p["qkv_w"] + p["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    drng = None
    if training and attn_drop > 0 and rng is not None:
        rng, drng = jax.random.split(rng)
    a = dot_product_attention(
        split_heads(q, n_head), split_heads(k, n_head),
        split_heads(v, n_head), mask=mask, causal=causal,
        dropout_p=attn_drop if training else 0.0, dropout_rng=drng,
        impl=attention_impl)
    a = merge_heads(a) @ p["proj_w"] + p["proj_b"]
    if training and hidden_drop > 0 and rng is not None:
        rng, drng = jax.random.split(rng)
        keep = jax.random.bernoulli(drng, 1 - hidden_drop, a.shape)
        a = jnp.where(keep, a / (1 - hidden_drop), 0.0)
    h = _layer_norm(h + a, p["ln1_g"], p["ln1_b"])
    f = act(h @ p["fc1_w"] + p["fc1_b"]) @ p["fc2_w"] + p["fc2_b"]
    if training and hidden_drop > 0 and rng is not None:
        rng, drng = jax.random.split(rng)
        keep = jax.random.bernoulli(drng, 1 - hidden_drop, f.shape)
        f = jnp.where(keep, f / (1 - hidden_drop), 0.0)
    return _layer_norm(h + f, p["ln2_g"], p["ln2_b"])


class TransformerLayer(Layer):
    """GPT-style decoder stack (reference:
    ``self_attention.py:46`` / ``TransformerLayer.scala:279``): token +
    learned position embeddings, ``n_block`` blocks, causal unless
    ``bidirectional=True``. Input: int ids (B, T); output (B, T, hidden).
    """

    def __init__(self, vocab: int, seq_len: int, n_block: int = 12,
                 hidden_size: int = 768, n_head: int = 12,
                 intermediate_size: Optional[int] = None,
                 hidden_drop: float = 0.1, attn_drop: float = 0.1,
                 initializer_range: float = 0.02,
                 bidirectional: bool = False, activation="gelu",
                 attention_impl: str = "auto", remat=False, **kwargs):
        """``remat``: per-block ``jax.checkpoint`` policy — ``False``
        (store all activations; fastest when they fit), ``True`` (full
        remat, ~4x-forward step cost for O(1) depth memory), or
        ``"dots"`` (save matmul outputs, recompute elementwise chains —
        the memory relief without the MXU recompute; same lever that
        took Llama from OOM to 0.42 MFU at S=512, ``llama.py:113``).
        Enables batch sizes that otherwise OOM (BERT-base B=256 at
        S=128 needs it on a 16G-HBM chip)."""
        super().__init__(**kwargs)
        if hidden_size % n_head:
            raise ValueError("hidden_size must divide by n_head")
        if attention_impl == "flash" and attn_drop > 0:
            raise ValueError(
                "attention_impl='flash' does not support attention dropout; "
                "pass attn_drop=0 (hidden_drop still applies)")
        if remat not in (False, True, "dots"):
            raise ValueError(f"remat must be False, True or 'dots', "
                             f"got {remat!r}")
        self.attention_impl = attention_impl
        self.remat = remat
        self.vocab = vocab
        self.seq_len = seq_len
        self.n_block = n_block
        self.hidden_size = hidden_size
        self.n_head = n_head
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.hidden_drop = hidden_drop
        self.attn_drop = attn_drop
        self.bidirectional = bidirectional
        self.act = get_activation_fn(activation)
        init = jax.nn.initializers.normal(stddev=initializer_range)
        self._init = init

    def build(self, rng, input_shape):
        k_tok, k_pos, k_blocks = jax.random.split(rng, 3)
        blocks = jax.vmap(
            lambda r: _block_params(r, self.hidden_size,
                                    self.intermediate_size, self._init)
        )(jax.random.split(k_blocks, self.n_block))
        return {
            "tok": self._init(k_tok, (self.vocab, self.hidden_size),
                              jnp.float32),
            "pos": self._init(k_pos, (self.seq_len, self.hidden_size),
                              jnp.float32),
            "blocks": blocks,
        }

    def _embed(self, params, ids):
        t = ids.shape[1]
        h = jnp.take(params["tok"], ids.astype(jnp.int32), axis=0)
        return h + params["pos"][:t]

    def _run_blocks(self, params, h, mask, training, rng):
        def raw_block(blk, h, brng):
            return _block_forward(blk, h, n_head=self.n_head, mask=mask,
                                  causal=not self.bidirectional,
                                  act=self.act,
                                  hidden_drop=self.hidden_drop,
                                  attn_drop=self.attn_drop,
                                  training=training, rng=brng,
                                  attention_impl=self.attention_impl)

        block_fn = raw_block
        if training and self.remat:
            # prevent_cse=False: the scan already prevents CSE; the
            # default barriers would block fusions in every iteration
            if self.remat == "dots":
                block_fn = jax.checkpoint(
                    raw_block, prevent_cse=False,
                    policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            else:
                block_fn = jax.checkpoint(raw_block, prevent_cse=False)

        def body(carry, blk):
            h, rng = carry
            brng = None
            if rng is not None:
                rng, brng = jax.random.split(rng)
            h = block_fn(blk, h, brng)
            return (h, rng), None

        rng = layer_rng(rng, self.name) if rng is not None else None
        (h, _), _ = jax.lax.scan(body, (h, rng), params["blocks"])
        return h

    def call(self, params, inputs, *, training=False, rng=None):
        h = self._embed(params, inputs)
        return self._run_blocks(params, h, None, training, rng)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.hidden_size,)


class BERT(TransformerLayer):
    """BERT encoder (reference: ``self_attention.py:235`` /
    ``BERT.scala:402``): token + position + segment embeddings with
    embedding LayerNorm, bidirectional blocks, plus a tanh pooler over
    [CLS]. Inputs: ``ids`` or ``[ids, token_type_ids, attention_mask]``.
    ``call`` returns the sequence output; ``pooled_output`` gives the [CLS]
    projection (the reference returns both as a tuple)."""

    def __init__(self, vocab: int, hidden_size: int = 768, n_block: int = 12,
                 n_head: int = 12, seq_len: int = 512,
                 intermediate_size: int = 3072, hidden_p_drop: float = 0.1,
                 attn_p_drop: float = 0.1, max_position_len: int = 512,
                 token_type_vocab: int = 2, initializer_range: float = 0.02,
                 **kwargs):
        super().__init__(vocab=vocab, seq_len=max(seq_len, max_position_len),
                         n_block=n_block, hidden_size=hidden_size,
                         n_head=n_head, intermediate_size=intermediate_size,
                         hidden_drop=hidden_p_drop, attn_drop=attn_p_drop,
                         initializer_range=initializer_range,
                         bidirectional=True, activation="gelu", **kwargs)
        self.token_type_vocab = token_type_vocab

    def build(self, rng, input_shape):
        base = super().build(rng, input_shape)
        k_seg, k_pool, k_ln = jax.random.split(jax.random.fold_in(rng, 7), 3)
        base["seg"] = self._init(k_seg, (self.token_type_vocab,
                                         self.hidden_size), jnp.float32)
        base["emb_ln_g"] = jnp.ones((self.hidden_size,), jnp.float32)
        base["emb_ln_b"] = jnp.zeros((self.hidden_size,), jnp.float32)
        base["pool_w"] = self._init(k_pool, (self.hidden_size,
                                             self.hidden_size), jnp.float32)
        base["pool_b"] = jnp.zeros((self.hidden_size,), jnp.float32)
        return base

    def _split_inputs(self, inputs):
        if isinstance(inputs, (list, tuple)):
            ids = inputs[0]
            seg = inputs[1] if len(inputs) > 1 else None
            mask = inputs[2] if len(inputs) > 2 else None
            return ids, seg, mask
        return inputs, None, None

    def call(self, params, inputs, *, training=False, rng=None):
        ids, seg, attn_mask = self._split_inputs(inputs)
        t = ids.shape[1]
        h = jnp.take(params["tok"], ids.astype(jnp.int32), axis=0)
        h = h + params["pos"][:t]
        if seg is not None:
            h = h + jnp.take(params["seg"], seg.astype(jnp.int32), axis=0)
        h = _layer_norm(h, params["emb_ln_g"], params["emb_ln_b"])
        mask = None
        if attn_mask is not None:
            mask = attn_mask[:, None, None, :].astype(bool)
        return self._run_blocks(params, h, mask, training, rng)

    def pooled_output(self, params, sequence_output):
        """[CLS] tanh pooler (reference BERT second output)."""
        return jnp.tanh(sequence_output[:, 0] @ params["pool_w"] +
                        params["pool_b"])

    def compute_output_shape(self, input_shape):
        shape = input_shape[0] if isinstance(input_shape, list) else \
            input_shape
        return tuple(shape) + (self.hidden_size,)
