"""Convolution / padding / upsampling layers, keras-1 style.

Rebuild of the reference's convolution layer set (Python
``pyzoo/zoo/pipeline/api/keras/layers/convolutional.py``, Scala
``pipeline/api/keras/layers/Convolution*.scala``). keras-1 argument names
(``nb_filter``, ``subsample``, ``border_mode``, ``dim_ordering``) preserved.

TPU note: convs execute internally in NHWC (the TPU-native layout, feeding
the MXU as implicit matmuls); ``dim_ordering="th"`` (the reference/BigDL
default, NCHW) is honored at the API boundary by transposing on entry/exit —
XLA fuses those transposes into the surrounding ops.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from zoo_tpu.pipeline.api.keras.engine.base import (
    Layer,
    get_activation_fn,
    get_initializer,
)


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _conv_out(size: Optional[int], k: int, s: int, mode: str) -> Optional[int]:
    if size is None:
        return None
    if mode == "same":
        return -(-size // s)
    return (size - k) // s + 1


class Convolution2D(Layer):
    """reference: ``Convolution2D`` (Scala ``Convolution2D.scala``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 init="glorot_uniform", activation=None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1),
                 dim_ordering: str = "th", bias: bool = True,
                 W_regularizer=None, b_regularizer=None, **kwargs):
        super().__init__(**kwargs)
        if border_mode not in ("valid", "same"):
            raise ValueError("border_mode must be 'valid' or 'same'")
        if dim_ordering not in ("th", "tf"):
            raise ValueError("dim_ordering must be 'th' or 'tf'")
        self.nb_filter = int(nb_filter)
        self.kernel = (int(nb_row), int(nb_col))
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def _in_channels(self, input_shape):
        return input_shape[1] if self.dim_ordering == "th" else input_shape[3]

    def build(self, rng, input_shape):
        cin = self._in_channels(input_shape)
        k = {"W": self.init(rng, self.kernel + (cin, self.nb_filter),
                            jnp.float32)}  # HWIO
        if self.bias:
            k["b"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return k

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW -> NHWC
        if "W_q" in params:  # int8 weights (quantize_model path)
            from zoo_tpu.ops.pallas.quant import quantized_conv2d
            y = quantized_conv2d(
                x, params["W_q"], params["W_scale"],
                strides=self.subsample,
                padding=self.border_mode.upper(),
                bias=params.get("b") if self.bias else None)
            if self.activation:
                y = self.activation(y)
            if self.dim_ordering == "th":
                y = jnp.transpose(y, (0, 3, 1, 2))
            return y
        # the one conv dispatch point (ops/pallas/conv.py): implicit-GEMM
        # Pallas kernel on TPU for supported shapes, the identical XLA
        # reference conv everywhere else (ZOO_CONV_IMPL overrides)
        from zoo_tpu.ops.pallas.conv import conv2d
        y = conv2d(x, params["W"], strides=self.subsample,
                   padding=self.border_mode.upper())
        if self.bias:
            y = y + params["b"]
        if self.activation:
            y = self.activation(y)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            n, c, h, w = input_shape
        else:
            n, h, w, c = input_shape
        oh = _conv_out(h, self.kernel[0], self.subsample[0], self.border_mode)
        ow = _conv_out(w, self.kernel[1], self.subsample[1], self.border_mode)
        if self.dim_ordering == "th":
            return (n, self.nb_filter, oh, ow)
        return (n, oh, ow, self.nb_filter)


Conv2D = Convolution2D


class Convolution1D(Layer):
    """reference: ``Convolution1D``; input (batch, steps, dim)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 init="glorot_uniform", activation=None,
                 border_mode: str = "valid", subsample_length: int = 1,
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        if border_mode not in ("valid", "same"):
            raise ValueError("border_mode must be 'valid' or 'same'")
        self.nb_filter = int(nb_filter)
        self.filter_length = int(filter_length)
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = int(subsample_length)
        self.bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        k = {"W": self.init(rng, (self.filter_length, cin, self.nb_filter),
                            jnp.float32)}
        if self.bias:
            k["b"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return k

    def call(self, params, inputs, *, training=False, rng=None):
        y = jax.lax.conv_general_dilated(
            inputs, params["W"], window_strides=(self.subsample,),
            padding=self.border_mode.upper(),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.bias:
            y = y + params["b"]
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, input_shape):
        n, steps, _ = input_shape
        return (n, _conv_out(steps, self.filter_length, self.subsample,
                             self.border_mode), self.nb_filter)


Conv1D = Convolution1D


class ZeroPadding2D(Layer):
    def __init__(self, padding=(1, 1), dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.padding = _pair(padding)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        ph, pw = self.padding
        if self.dim_ordering == "th":
            pad = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        else:
            pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        return jnp.pad(inputs, pad)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        hx, wx = (2, 3) if self.dim_ordering == "th" else (1, 2)
        if s[hx] is not None:
            s[hx] += 2 * self.padding[0]
        if s[wx] is not None:
            s[wx] += 2 * self.padding[1]
        return tuple(s)


class ZeroPadding1D(Layer):
    def __init__(self, padding: int = 1, **kwargs):
        super().__init__(**kwargs)
        self.padding = int(padding)

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.pad(inputs, ((0, 0), (self.padding, self.padding), (0, 0)))

    def compute_output_shape(self, input_shape):
        n, steps, d = input_shape
        return (n, None if steps is None else steps + 2 * self.padding, d)


class UpSampling2D(Layer):
    def __init__(self, size=(2, 2), dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.size = _pair(size)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        sh, sw = self.size
        if self.dim_ordering == "th":
            return jnp.repeat(jnp.repeat(inputs, sh, axis=2), sw, axis=3)
        return jnp.repeat(jnp.repeat(inputs, sh, axis=1), sw, axis=2)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        hx, wx = (2, 3) if self.dim_ordering == "th" else (1, 2)
        if s[hx] is not None:
            s[hx] *= self.size[0]
        if s[wx] is not None:
            s[wx] *= self.size[1]
        return tuple(s)


class UpSampling1D(Layer):
    def __init__(self, length: int = 2, **kwargs):
        super().__init__(**kwargs)
        self.length = int(length)

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.repeat(inputs, self.length, axis=1)

    def compute_output_shape(self, input_shape):
        n, steps, d = input_shape
        return (n, None if steps is None else steps * self.length, d)


class Cropping2D(Layer):
    def __init__(self, cropping=((0, 0), (0, 0)), dim_ordering: str = "th",
                 **kwargs):
        super().__init__(**kwargs)
        self.cropping = tuple(tuple(int(v) for v in c) for c in cropping)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        (t, b), (l, r) = self.cropping
        if self.dim_ordering == "th":
            return inputs[:, :, t:inputs.shape[2] - b,
                          l:inputs.shape[3] - r]
        return inputs[:, t:inputs.shape[1] - b, l:inputs.shape[2] - r, :]

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        hx, wx = (2, 3) if self.dim_ordering == "th" else (1, 2)
        (t, b), (l, r) = self.cropping
        if s[hx] is not None:
            s[hx] -= t + b
        if s[wx] is not None:
            s[wx] -= l + r
        return tuple(s)


class Cropping1D(Layer):
    def __init__(self, cropping=(1, 1), **kwargs):
        super().__init__(**kwargs)
        self.cropping = tuple(int(v) for v in cropping)

    def call(self, params, inputs, *, training=False, rng=None):
        l, r = self.cropping
        return inputs[:, l:inputs.shape[1] - r, :]

    def compute_output_shape(self, input_shape):
        n, steps, d = input_shape
        return (n, None if steps is None else steps - sum(self.cropping), d)


class SpatialDropout2D(Layer):
    """Drop whole feature maps (reference: ``SpatialDropout2D``)."""

    def __init__(self, p: float = 0.5, dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.p = float(p)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        if not training or self.p <= 0:
            return inputs
        from zoo_tpu.pipeline.api.keras.engine.base import layer_rng
        keep = 1.0 - self.p
        if self.dim_ordering == "th":
            shape = (inputs.shape[0], inputs.shape[1], 1, 1)
        else:
            shape = (inputs.shape[0], 1, 1, inputs.shape[3])
        mask = jax.random.bernoulli(layer_rng(rng, self.name), keep, shape)
        return jnp.where(mask, inputs / keep, 0.0)


class SpatialDropout1D(Layer):
    def __init__(self, p: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.p = float(p)

    def call(self, params, inputs, *, training=False, rng=None):
        if not training or self.p <= 0:
            return inputs
        from zoo_tpu.pipeline.api.keras.engine.base import layer_rng
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(
            layer_rng(rng, self.name), keep,
            (inputs.shape[0], 1, inputs.shape[2]))
        return jnp.where(mask, inputs / keep, 0.0)
