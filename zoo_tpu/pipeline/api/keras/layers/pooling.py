"""Pooling layers, keras-1 style (reference: Python
``pyzoo/zoo/pipeline/api/keras/layers/pooling.py``, Scala
``pipeline/api/keras/layers/*Pooling*.scala``). NHWC internally (TPU
layout); ``dim_ordering="th"`` handled by transposition like the convs.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import Layer
from zoo_tpu.pipeline.api.keras.layers.convolutional import _conv_out, _pair


def _reduce_window(x, init, op, window, strides, padding):
    return jax.lax.reduce_window(x, init, op, window, strides, padding)


class _Pool2D(Layer):
    def __init__(self, pool_size=(2, 2), strides=None,
                 border_mode: str = "valid", dim_ordering: str = "th",
                 **kwargs):
        super().__init__(**kwargs)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering

    def _pool(self, x):  # NHWC
        raise NotImplementedError

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 1))
        y = self._pool(x)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            n, c, h, w = input_shape
        else:
            n, h, w, c = input_shape
        oh = _conv_out(h, self.pool_size[0], self.strides[0], self.border_mode)
        ow = _conv_out(w, self.pool_size[1], self.strides[1], self.border_mode)
        return (n, c, oh, ow) if self.dim_ordering == "th" else (n, oh, ow, c)


class MaxPooling2D(_Pool2D):
    def _pool(self, x):
        return _reduce_window(
            x, -jnp.inf, jax.lax.max,
            (1,) + self.pool_size + (1,), (1,) + self.strides + (1,),
            self.border_mode.upper())


class AveragePooling2D(_Pool2D):
    def _pool(self, x):
        window = (1,) + self.pool_size + (1,)
        strides = (1,) + self.strides + (1,)
        summed = _reduce_window(x, 0.0, jax.lax.add, window, strides,
                                self.border_mode.upper())
        counts = _reduce_window(jnp.ones_like(x), 0.0, jax.lax.add, window,
                                strides, self.border_mode.upper())
        return summed / counts


class _Pool1D(Layer):
    def __init__(self, pool_length: int = 2, stride=None,
                 border_mode: str = "valid", **kwargs):
        super().__init__(**kwargs)
        self.pool_length = int(pool_length)
        self.stride = int(stride) if stride is not None else self.pool_length
        self.border_mode = border_mode

    def call(self, params, inputs, *, training=False, rng=None):
        raise NotImplementedError

    def compute_output_shape(self, input_shape):
        n, steps, d = input_shape
        return (n, _conv_out(steps, self.pool_length, self.stride,
                             self.border_mode), d)


class MaxPooling1D(_Pool1D):
    def call(self, params, inputs, *, training=False, rng=None):
        return _reduce_window(
            inputs, -jnp.inf, jax.lax.max,
            (1, self.pool_length, 1), (1, self.stride, 1),
            self.border_mode.upper())


class AveragePooling1D(_Pool1D):
    def call(self, params, inputs, *, training=False, rng=None):
        window, strides = (1, self.pool_length, 1), (1, self.stride, 1)
        summed = _reduce_window(inputs, 0.0, jax.lax.add, window, strides,
                                self.border_mode.upper())
        counts = _reduce_window(jnp.ones_like(inputs), 0.0, jax.lax.add,
                                window, strides, self.border_mode.upper())
        return summed / counts


class GlobalMaxPooling2D(Layer):
    def __init__(self, dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        axes = (2, 3) if self.dim_ordering == "th" else (1, 2)
        return jnp.max(inputs, axis=axes)

    def compute_output_shape(self, input_shape):
        c = input_shape[1] if self.dim_ordering == "th" else input_shape[3]
        return (input_shape[0], c)


class GlobalAveragePooling2D(GlobalMaxPooling2D):
    def call(self, params, inputs, *, training=False, rng=None):
        axes = (2, 3) if self.dim_ordering == "th" else (1, 2)
        return jnp.mean(inputs, axis=axes)


class GlobalMaxPooling1D(Layer):
    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.max(inputs, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], input_shape[2])


class GlobalAveragePooling1D(GlobalMaxPooling1D):
    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.mean(inputs, axis=1)
