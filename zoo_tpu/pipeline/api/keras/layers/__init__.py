from zoo_tpu.pipeline.api.keras.layers.core import (
    Activation,
    BatchNormalization,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    GaussianNoise,
    InputLayer,
    Lambda,
    Merge,
    Permute,
    RepeatVector,
    Reshape,
    merge,
)
from zoo_tpu.pipeline.api.keras.layers.convolutional import (
    Conv1D,
    Conv2D,
    Convolution1D,
    Convolution2D,
    Cropping1D,
    Cropping2D,
    SpatialDropout1D,
    SpatialDropout2D,
    UpSampling1D,
    UpSampling2D,
    ZeroPadding1D,
    ZeroPadding2D,
)
from zoo_tpu.pipeline.api.keras.layers.pooling import (
    AveragePooling1D,
    AveragePooling2D,
    GlobalAveragePooling1D,
    GlobalAveragePooling2D,
    GlobalMaxPooling1D,
    GlobalMaxPooling2D,
    MaxPooling1D,
    MaxPooling2D,
)
from zoo_tpu.pipeline.api.keras.layers.recurrent import (
    GRU,
    LSTM,
    Bidirectional,
    SimpleRNN,
    TimeDistributed,
)
from zoo_tpu.pipeline.api.keras.layers.advanced import (
    ELU,
    Highway,
    LeakyReLU,
    MaxoutDense,
    PReLU,
    SReLU,
    ThresholdedReLU,
)
from zoo_tpu.pipeline.api.keras.layers.self_attention import (
    BERT,
    LayerNorm,
    TransformerLayer,
)

from zoo_tpu.pipeline.api.keras.layers.extras import (  # noqa: F401
    AddConstant, BinaryThreshold, CAdd, CMul, Exp, ExpandDim,
    GaussianDropout, GaussianSampler, GetShape, HardShrink, HardTanh,
    Identity, LRN2D, Log, Masking, Max, MulConstant, Narrow, Negative,
    Power, RReLU, ResizeBilinear, Scale, Select, SoftShrink, Sqrt, Square,
    Squeeze, Threshold, WithinChannelLRN2D,
)
from zoo_tpu.pipeline.api.keras.layers.compat_extras import (  # noqa: F401
    KerasLayerWrapper,
    Mul,
    SparseDense,
    SparseEmbedding,
)
from zoo_tpu.pipeline.api.keras.engine.topology import Input  # noqa: F401
from zoo_tpu.pipeline.api.keras.layers.conv_extras import (  # noqa: F401
    DepthwiseConvolution2D,
    AtrousConvolution1D, AtrousConvolution2D, AveragePooling3D, ConvLSTM2D,
    Convolution3D, Cropping3D, Deconvolution2D, GlobalAveragePooling3D,
    GlobalMaxPooling3D, LocallyConnected1D, LocallyConnected2D,
    MaxPooling3D, SeparableConvolution2D, ShareConvolution2D,
    SpatialDropout3D, UpSampling3D, WordEmbedding, ZeroPadding3D,
)

__all__ = [
    "Activation", "BatchNormalization", "Dense", "Dropout", "Embedding",
    "Flatten", "GaussianNoise", "InputLayer", "Lambda", "Merge", "Permute",
    "RepeatVector", "Reshape", "merge",
    "Conv1D", "Conv2D", "Convolution1D", "Convolution2D", "Cropping1D",
    "Cropping2D", "SpatialDropout1D", "SpatialDropout2D", "UpSampling1D",
    "UpSampling2D", "ZeroPadding1D", "ZeroPadding2D",
    "AveragePooling1D", "AveragePooling2D", "GlobalAveragePooling1D",
    "GlobalAveragePooling2D", "GlobalMaxPooling1D", "GlobalMaxPooling2D",
    "MaxPooling1D", "MaxPooling2D",
    "GRU", "LSTM", "Bidirectional", "SimpleRNN", "TimeDistributed",
    "ELU", "Highway", "LeakyReLU", "MaxoutDense", "PReLU", "SReLU",
    "ThresholdedReLU",
    "BERT", "LayerNorm", "TransformerLayer",
    "AddConstant", "BinaryThreshold", "CAdd", "CMul", "Exp", "ExpandDim",
    "GaussianDropout", "GaussianSampler", "GetShape", "HardShrink",
    "HardTanh", "Identity", "LRN2D", "Log", "Masking", "Max", "MulConstant",
    "Narrow", "Negative", "Power", "RReLU", "ResizeBilinear", "Scale",
    "Select", "SoftShrink", "Sqrt", "Square", "Squeeze", "Threshold",
    "WithinChannelLRN2D",
    "AtrousConvolution1D", "AtrousConvolution2D", "AveragePooling3D",
    "ConvLSTM2D", "Convolution3D", "Cropping3D", "Deconvolution2D",
    "DepthwiseConvolution2D",
    "GlobalAveragePooling3D", "GlobalMaxPooling3D", "LocallyConnected1D",
    "LocallyConnected2D", "MaxPooling3D", "SeparableConvolution2D",
    "ShareConvolution2D", "SpatialDropout3D", "UpSampling3D",
    "WordEmbedding", "ZeroPadding3D",
    "Input", "KerasLayerWrapper", "Mul", "SparseDense", "SparseEmbedding",
]
