from zoo_tpu.pipeline.api.keras.layers.core import (
    Activation,
    BatchNormalization,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    GaussianNoise,
    InputLayer,
    Lambda,
    Merge,
    Permute,
    RepeatVector,
    Reshape,
    merge,
)

__all__ = [
    "Activation", "BatchNormalization", "Dense", "Dropout", "Embedding",
    "Flatten", "GaussianNoise", "InputLayer", "Lambda", "Merge", "Permute",
    "RepeatVector", "Reshape", "merge",
]
