from zoo_tpu.pipeline.api.keras.layers.core import (
    Activation,
    BatchNormalization,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    GaussianNoise,
    InputLayer,
    Lambda,
    Merge,
    Permute,
    RepeatVector,
    Reshape,
    merge,
)
from zoo_tpu.pipeline.api.keras.layers.convolutional import (
    Conv1D,
    Conv2D,
    Convolution1D,
    Convolution2D,
    Cropping1D,
    Cropping2D,
    SpatialDropout1D,
    SpatialDropout2D,
    UpSampling1D,
    UpSampling2D,
    ZeroPadding1D,
    ZeroPadding2D,
)
from zoo_tpu.pipeline.api.keras.layers.pooling import (
    AveragePooling1D,
    AveragePooling2D,
    GlobalAveragePooling1D,
    GlobalAveragePooling2D,
    GlobalMaxPooling1D,
    GlobalMaxPooling2D,
    MaxPooling1D,
    MaxPooling2D,
)
from zoo_tpu.pipeline.api.keras.layers.recurrent import (
    GRU,
    LSTM,
    Bidirectional,
    SimpleRNN,
    TimeDistributed,
)
from zoo_tpu.pipeline.api.keras.layers.advanced import (
    ELU,
    Highway,
    LeakyReLU,
    MaxoutDense,
    PReLU,
    SReLU,
    ThresholdedReLU,
)
from zoo_tpu.pipeline.api.keras.layers.self_attention import (
    BERT,
    LayerNorm,
    TransformerLayer,
)

__all__ = [
    "Activation", "BatchNormalization", "Dense", "Dropout", "Embedding",
    "Flatten", "GaussianNoise", "InputLayer", "Lambda", "Merge", "Permute",
    "RepeatVector", "Reshape", "merge",
    "Conv1D", "Conv2D", "Convolution1D", "Convolution2D", "Cropping1D",
    "Cropping2D", "SpatialDropout1D", "SpatialDropout2D", "UpSampling1D",
    "UpSampling2D", "ZeroPadding1D", "ZeroPadding2D",
    "AveragePooling1D", "AveragePooling2D", "GlobalAveragePooling1D",
    "GlobalAveragePooling2D", "GlobalMaxPooling1D", "GlobalMaxPooling2D",
    "MaxPooling1D", "MaxPooling2D",
    "GRU", "LSTM", "Bidirectional", "SimpleRNN", "TimeDistributed",
    "ELU", "Highway", "LeakyReLU", "MaxoutDense", "PReLU", "SReLU",
    "ThresholdedReLU",
    "BERT", "LayerNorm", "TransformerLayer",
]
