"""3D conv/pool family, separable/deconv/locally-connected convs,
ConvLSTM2D, WordEmbedding — the rest of the reference's conv layer zoo
(Python ``pyzoo/zoo/pipeline/api/keras/layers/convolutional.py``,
``pooling.py``, ``convolutional_recurrent.py``, ``local.py``,
``embeddings.py``; Scala ``pipeline/api/keras/layers/*.scala``).

All convs run NDHWC/NHWC internally (TPU-native channel-last feeding the
MXU); ``dim_ordering="th"`` transposes at the boundary like the 2D layers.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from zoo_tpu.pipeline.api.keras.engine.base import (
    Layer,
    get_activation_fn,
    get_initializer,
    layer_rng,
)
from zoo_tpu.pipeline.api.keras.layers.convolutional import Convolution2D


def _tup(v, n):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _out_dim(size, k, s, mode):
    if size is None:
        return None
    if mode == "same":
        return -(-size // s)
    return (size - k) // s + 1


class Convolution3D(Layer):
    """reference: ``Convolution3D`` (th layout (B, C, D, H, W))."""

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, init="glorot_uniform", activation=None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int, int] = (1, 1, 1),
                 dim_ordering: str = "th", bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = int(nb_filter)
        self.kernel = (int(kernel_dim1), int(kernel_dim2), int(kernel_dim3))
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _tup(subsample, 3)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[1] if self.dim_ordering == "th" else input_shape[4]
        p = {"W": self.init(rng, self.kernel + (cin, self.nb_filter),
                            jnp.float32)}  # DHWIO
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 4, 1))  # NCDHW -> NDHWC
        y = jax.lax.conv_general_dilated(
            x, params["W"], window_strides=self.subsample,
            padding=self.border_mode.upper(),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.bias:
            y = y + params["b"]
        if self.activation:
            y = self.activation(y)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 4, 1, 2, 3))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            b, c, d, h, w = input_shape
        else:
            b, d, h, w, c = input_shape
        od = _out_dim(d, self.kernel[0], self.subsample[0], self.border_mode)
        oh = _out_dim(h, self.kernel[1], self.subsample[1], self.border_mode)
        ow = _out_dim(w, self.kernel[2], self.subsample[2], self.border_mode)
        if self.dim_ordering == "th":
            return (b, self.nb_filter, od, oh, ow)
        return (b, od, oh, ow, self.nb_filter)


class AtrousConvolution2D(Layer):
    """Dilated conv (reference: ``AtrousConvolution2D``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 init="glorot_uniform", activation=None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1),
                 atrous_rate: Tuple[int, int] = (1, 1),
                 dim_ordering: str = "th", bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = int(nb_filter)
        self.kernel = (int(nb_row), int(nb_col))
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _tup(subsample, 2)
        self.rate = _tup(atrous_rate, 2)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[1] if self.dim_ordering == "th" else input_shape[3]
        p = {"W": self.init(rng, self.kernel + (cin, self.nb_filter),
                            jnp.float32)}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 1))
        y = jax.lax.conv_general_dilated(
            x, params["W"], window_strides=self.subsample,
            padding=self.border_mode.upper(), rhs_dilation=self.rate,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.bias:
            y = y + params["b"]
        if self.activation:
            y = self.activation(y)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            b, c, h, w = input_shape
        else:
            b, h, w, c = input_shape
        ek = tuple(self.rate[i] * (self.kernel[i] - 1) + 1 for i in (0, 1))
        oh = _out_dim(h, ek[0], self.subsample[0], self.border_mode)
        ow = _out_dim(w, ek[1], self.subsample[1], self.border_mode)
        if self.dim_ordering == "th":
            return (b, self.nb_filter, oh, ow)
        return (b, oh, ow, self.nb_filter)


class AtrousConvolution1D(Layer):
    """reference: ``AtrousConvolution1D`` — input (B, T, C)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 init="glorot_uniform", activation=None,
                 border_mode: str = "valid", subsample_length: int = 1,
                 atrous_rate: int = 1, bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = int(nb_filter)
        self.k = int(filter_length)
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.stride = int(subsample_length)
        self.rate = int(atrous_rate)
        self.bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        p = {"W": self.init(rng, (self.k, cin, self.nb_filter), jnp.float32)}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        y = jax.lax.conv_general_dilated(
            inputs, params["W"], window_strides=(self.stride,),
            padding=self.border_mode.upper(), rhs_dilation=(self.rate,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.bias:
            y = y + params["b"]
        if self.activation:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape):
        b, t, c = input_shape
        ek = self.rate * (self.k - 1) + 1
        return (b, _out_dim(t, ek, self.stride, self.border_mode),
                self.nb_filter)


class Deconvolution2D(Layer):
    """Transposed conv (reference: ``Deconvolution2D``; th layout)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 init="glorot_uniform", activation=None,
                 subsample: Tuple[int, int] = (1, 1),
                 border_mode: str = "valid",
                 dim_ordering: str = "th", bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        if border_mode != "valid":
            raise ValueError("Deconvolution2D supports border_mode='valid' "
                             "only (the reference's constraint too)")
        self.nb_filter = int(nb_filter)
        self.kernel = (int(nb_row), int(nb_col))
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.subsample = _tup(subsample, 2)
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[1] if self.dim_ordering == "th" else input_shape[3]
        p = {"W": self.init(rng, self.kernel + (self.nb_filter, cin),
                            jnp.float32)}  # HWOI (deconv: out before in)
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 1))
        kh, kw = self.kernel
        sh, sw = self.subsample
        # fractionally-strided conv with the spatially-flipped kernel
        w = jnp.flip(params["W"], (0, 1))  # HWOI
        w = jnp.transpose(w, (0, 1, 3, 2))  # -> HWIO with I=cin
        pad = ((kh - 1, kh - 1), (kw - 1, kw - 1))
        y = jax.lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding=pad,
            lhs_dilation=(sh, sw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.bias:
            y = y + params["b"]
        if self.activation:
            y = self.activation(y)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            b, c, h, w = input_shape
        else:
            b, h, w, c = input_shape
        oh = None if h is None else (h - 1) * self.subsample[0] + \
            self.kernel[0]
        ow = None if w is None else (w - 1) * self.subsample[1] + \
            self.kernel[1]
        if self.dim_ordering == "th":
            return (b, self.nb_filter, oh, ow)
        return (b, oh, ow, self.nb_filter)


class SeparableConvolution2D(Layer):
    """Depthwise conv then 1x1 pointwise (reference:
    ``SeparableConvolution2D``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 init="glorot_uniform", activation=None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1),
                 depth_multiplier: int = 1,
                 dim_ordering: str = "th", bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = int(nb_filter)
        self.kernel = (int(nb_row), int(nb_col))
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _tup(subsample, 2)
        self.mult = int(depth_multiplier)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[1] if self.dim_ordering == "th" else input_shape[3]
        k1, k2 = jax.random.split(rng)
        p = {"depth_W": self.init(
                 k1, self.kernel + (1, cin * self.mult), jnp.float32),
             "point_W": self.init(
                 k2, (1, 1, cin * self.mult, self.nb_filter), jnp.float32)}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 1))
        cin = x.shape[-1]
        y = jax.lax.conv_general_dilated(
            x, params["depth_W"], window_strides=self.subsample,
            padding=self.border_mode.upper(), feature_group_count=cin,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jax.lax.conv_general_dilated(
            y, params["point_W"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.bias:
            y = y + params["b"]
        if self.activation:
            y = self.activation(y)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            b, c, h, w = input_shape
        else:
            b, h, w, c = input_shape
        oh = _out_dim(h, self.kernel[0], self.subsample[0], self.border_mode)
        ow = _out_dim(w, self.kernel[1], self.subsample[1], self.border_mode)
        if self.dim_ordering == "th":
            return (b, self.nb_filter, oh, ow)
        return (b, oh, ow, self.nb_filter)


class ShareConvolution2D(Convolution2D):
    """reference: ``ShareConvolution2D`` — same math as Convolution2D (a
    standard conv already shares weights spatially)."""


class LocallyConnected1D(Layer):
    """Unshared conv over time (reference: ``LocallyConnected1D``)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation=None, subsample_length: int = 1,
                 border_mode: str = "valid", bias: bool = True,
                 init="glorot_uniform", **kwargs):
        super().__init__(**kwargs)
        if border_mode != "valid":
            raise ValueError("LocallyConnected1D supports border_mode="
                             "'valid' only (like the reference)")
        self.nb_filter = int(nb_filter)
        self.k = int(filter_length)
        self.stride = int(subsample_length)
        self.activation = get_activation_fn(activation)
        self.bias = bias
        self.init = get_initializer(init)

    def build(self, rng, input_shape):
        t, c = input_shape[1], input_shape[2]
        ot = (t - self.k) // self.stride + 1
        p = {"W": self.init(rng, (ot, self.k * c, self.nb_filter),
                            jnp.float32)}
        if self.bias:
            p["b"] = jnp.zeros((ot, self.nb_filter), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        b, t, c = inputs.shape
        ot = params["W"].shape[0]
        idx = jnp.arange(ot) * self.stride
        patches = jax.vmap(
            lambda i: jax.lax.dynamic_slice_in_dim(inputs, i, self.k, 1),
            out_axes=1)(idx)                      # (B, OT, K, C)
        patches = patches.reshape(b, ot, self.k * c)
        y = jnp.einsum("bok,okf->bof", patches, params["W"])
        if self.bias:
            y = y + params["b"]
        if self.activation:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape):
        b, t, c = input_shape
        ot = None if t is None else (t - self.k) // self.stride + 1
        return (b, ot, self.nb_filter)


class LocallyConnected2D(Layer):
    """Unshared 2D conv (reference: ``LocallyConnected2D``; th layout)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample: Tuple[int, int] = (1, 1),
                 border_mode: str = "valid", dim_ordering: str = "th",
                 bias: bool = True, init="glorot_uniform", **kwargs):
        super().__init__(**kwargs)
        if border_mode != "valid":
            raise ValueError("LocallyConnected2D supports border_mode="
                             "'valid' only (like the reference)")
        self.nb_filter = int(nb_filter)
        self.kernel = (int(nb_row), int(nb_col))
        self.subsample = _tup(subsample, 2)
        self.activation = get_activation_fn(activation)
        self.dim_ordering = dim_ordering
        self.bias = bias
        self.init = get_initializer(init)

    def _hw(self, input_shape):
        return (input_shape[2], input_shape[3]) \
            if self.dim_ordering == "th" else (input_shape[1],
                                               input_shape[2])

    def build(self, rng, input_shape):
        h, w = self._hw(input_shape)
        c = input_shape[1] if self.dim_ordering == "th" else input_shape[3]
        oh = (h - self.kernel[0]) // self.subsample[0] + 1
        ow = (w - self.kernel[1]) // self.subsample[1] + 1
        p = {"W": self.init(
            rng, (oh * ow, self.kernel[0] * self.kernel[1] * c,
                  self.nb_filter), jnp.float32)}
        if self.bias:
            p["b"] = jnp.zeros((oh * ow, self.nb_filter), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 1))  # NHWC
        b, h, w, c = x.shape
        kh, kw = self.kernel
        sh, sw = self.subsample
        oh = (h - kh) // sh + 1
        ow = (w - kw) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))  # (B,OH,OW,C*KH*KW)
        patches = patches.reshape(b, oh * ow, -1)
        y = jnp.einsum("bpk,pkf->bpf", patches, params["W"])
        if self.bias:
            y = y + params["b"]
        y = y.reshape(b, oh, ow, self.nb_filter)
        if self.activation:
            y = self.activation(y)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        h, w = self._hw(input_shape)
        oh = None if h is None else (h - self.kernel[0]) // \
            self.subsample[0] + 1
        ow = None if w is None else (w - self.kernel[1]) // \
            self.subsample[1] + 1
        if self.dim_ordering == "th":
            return (input_shape[0], self.nb_filter, oh, ow)
        return (input_shape[0], oh, ow, self.nb_filter)


class ConvLSTM2D(Layer):
    """Convolutional LSTM over a (B, T, C, H, W) sequence (reference:
    ``ConvLSTM2D``; th layout, square kernel). Runs under ``lax.scan`` —
    one compiled step body for the whole sequence."""

    def __init__(self, nb_filter: int, nb_kernel: int,
                 activation="tanh", inner_activation="hard_sigmoid",
                 dim_ordering: str = "th", border_mode: str = "same",
                 subsample: Tuple[int, int] = (1, 1),
                 return_sequences: bool = False,
                 init="glorot_uniform", **kwargs):
        super().__init__(**kwargs)
        if dim_ordering != "th":
            raise ValueError("ConvLSTM2D supports dim_ordering='th' (the "
                             "reference only ships th)")
        if border_mode != "same" or _tup(subsample, 2) != (1, 1):
            raise ValueError("ConvLSTM2D supports border_mode='same', "
                             "subsample=(1,1) (reference constraint)")
        self.nb_filter = int(nb_filter)
        self.k = int(nb_kernel)
        self.activation = get_activation_fn(activation)
        self.inner_activation = get_activation_fn(inner_activation)
        self.return_sequences = return_sequences
        self.init = get_initializer(init)

    def build(self, rng, input_shape):
        c = input_shape[2]
        k1, k2 = jax.random.split(rng)
        return {
            "W": self.init(k1, (self.k, self.k, c, 4 * self.nb_filter),
                           jnp.float32),
            "U": self.init(k2, (self.k, self.k, self.nb_filter,
                                4 * self.nb_filter), jnp.float32),
            "b": jnp.zeros((4 * self.nb_filter,), jnp.float32),
        }

    def _conv(self, x, w):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def call(self, params, inputs, *, training=False, rng=None):
        x = jnp.transpose(inputs, (1, 0, 3, 4, 2))  # (T, B, H, W, C)
        b, h, w = x.shape[1], x.shape[2], x.shape[3]
        f = self.nb_filter
        h0 = jnp.zeros((b, h, w, f), inputs.dtype)
        c0 = jnp.zeros((b, h, w, f), inputs.dtype)

        def step(carry, xt):
            hp, cp = carry
            z = self._conv(xt, params["W"]) + self._conv(hp, params["U"]) \
                + params["b"]
            zi, zf, zc, zo = jnp.split(z, 4, axis=-1)
            i = self.inner_activation(zi)
            fg = self.inner_activation(zf)
            cn = fg * cp + i * self.activation(zc)
            o = self.inner_activation(zo)
            hn = o * self.activation(cn)
            return (hn, cn), hn

        (hT, _), hs = jax.lax.scan(step, (h0, c0), x)
        if self.return_sequences:
            return jnp.transpose(hs, (1, 0, 4, 2, 3))  # (B,T,F,H,W)
        return jnp.transpose(hT, (0, 3, 1, 2))  # (B,F,H,W)

    def compute_output_shape(self, input_shape):
        b, t, c, h, w = input_shape
        if self.return_sequences:
            return (b, t, self.nb_filter, h, w)
        return (b, self.nb_filter, h, w)


class WordEmbedding(Layer):
    """Frozen pretrained word embedding (reference: ``WordEmbedding`` —
    loads GloVe-style vectors, not trainable). The table rides in the
    ``stats`` subtree so the train step never takes its gradient."""

    def __init__(self, embedding_matrix: np.ndarray, **kwargs):
        super().__init__(**kwargs)
        self.matrix = np.asarray(embedding_matrix, np.float32)

    @classmethod
    def from_glove(cls, path: str, word_index: dict, **kwargs):
        from zoo_tpu.feature.text import load_glove_matrix
        return cls(load_glove_matrix(path, word_index), **kwargs)

    def build(self, rng, input_shape):
        return {"stats": {"table": jnp.asarray(self.matrix)}}

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.take(params["stats"]["table"],
                        inputs.astype(jnp.int32), axis=0)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.matrix.shape[1],)


# ------------------------------------------------- 3D pool/pad/resize

class _Pool3D(Layer):
    """th layout (B, C, D, H, W); pools run channel-last internally."""

    def __init__(self, pool_size=(2, 2, 2), strides=None,
                 border_mode: str = "valid", dim_ordering: str = "th",
                 **kwargs):
        super().__init__(**kwargs)
        self.pool = _tup(pool_size, 3)
        self.strides = _tup(strides, 3) if strides is not None else self.pool
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering

    def _reduce(self, x):
        raise NotImplementedError

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 4, 1))
        y = self._reduce(x)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 4, 1, 2, 3))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            b, c, d, h, w = input_shape
        else:
            b, d, h, w, c = input_shape
        od = _out_dim(d, self.pool[0], self.strides[0], self.border_mode)
        oh = _out_dim(h, self.pool[1], self.strides[1], self.border_mode)
        ow = _out_dim(w, self.pool[2], self.strides[2], self.border_mode)
        if self.dim_ordering == "th":
            return (b, c, od, oh, ow)
        return (b, od, oh, ow, c)


class MaxPooling3D(_Pool3D):
    def _reduce(self, x):
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1,) + self.pool + (1,),
            (1,) + self.strides + (1,), self.border_mode.upper())


class AveragePooling3D(_Pool3D):
    def _reduce(self, x):
        s = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1,) + self.pool + (1,),
            (1,) + self.strides + (1,), self.border_mode.upper())
        return s / float(np.prod(self.pool))


class GlobalAveragePooling3D(Layer):
    def __init__(self, dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        return jnp.mean(inputs, axis=axes)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],
                input_shape[1 if self.dim_ordering == "th" else 4])


class GlobalMaxPooling3D(GlobalAveragePooling3D):
    def call(self, params, inputs, *, training=False, rng=None):
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        return jnp.max(inputs, axis=axes)


class UpSampling3D(Layer):
    def __init__(self, size=(2, 2, 2), dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.size = _tup(size, 3)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        y = inputs
        for ax, r in zip(axes, self.size):
            y = jnp.repeat(y, r, axis=ax)
        return y

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        for ax, r in zip(axes, self.size):
            if out[ax] is not None:
                out[ax] *= r
        return tuple(out)


class ZeroPadding3D(Layer):
    def __init__(self, padding=(1, 1, 1), dim_ordering: str = "th",
                 **kwargs):
        super().__init__(**kwargs)
        self.padding = _tup(padding, 3)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        p = self.padding
        cfg = [(0, 0)] * 5
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        for ax, v in zip(axes, p):
            cfg[ax] = (v, v)
        return jnp.pad(inputs, cfg)

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        for ax, v in zip(axes, self.padding):
            if out[ax] is not None:
                out[ax] += 2 * v
        return tuple(out)


class Cropping3D(Layer):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)),
                 dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.cropping = tuple(_tup(c, 2) for c in cropping)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        ix = [slice(None)] * 5
        for ax, (lo, hi) in zip(axes, self.cropping):
            ix[ax] = slice(lo, inputs.shape[ax] - hi)
        return inputs[tuple(ix)]

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        for ax, (lo, hi) in zip(axes, self.cropping):
            if out[ax] is not None:
                out[ax] -= lo + hi
        return tuple(out)


class SpatialDropout3D(Layer):
    """Drop whole channels of a 3D volume (reference: ``SpatialDropout3D``)."""

    def __init__(self, p: float = 0.5, dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.p = float(p)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        if not training or rng is None or self.p <= 0:
            return inputs
        r = layer_rng(rng, self.name)
        if self.dim_ordering == "th":
            shape = (inputs.shape[0], inputs.shape[1], 1, 1, 1)
        else:
            shape = (inputs.shape[0], 1, 1, 1, inputs.shape[4])
        keep = jax.random.bernoulli(r, 1.0 - self.p, shape)
        return jnp.where(keep, inputs / (1.0 - self.p), 0.0)


class DepthwiseConvolution2D(Layer):
    """Per-channel spatial conv without the pointwise mix (the depthwise
    half of ``SeparableConvolution2D``; net-new layer the MobileNet
    configs in ``models/image/imageclassification`` need — the reference
    ships MobileNet only as a pretrained BigDL file)."""

    def __init__(self, nb_row: int, nb_col: int, init="glorot_uniform",
                 activation=None, border_mode: str = "valid",
                 subsample=(1, 1), depth_multiplier: int = 1,
                 dim_ordering: str = "th", bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.kernel = (int(nb_row), int(nb_col))
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _tup(subsample, 2)
        self.mult = int(depth_multiplier)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[1] if self.dim_ordering == "th" else input_shape[3]
        p = {"W": self.init(rng, self.kernel + (1, cin * self.mult),
                            jnp.float32)}
        if self.bias:
            p["b"] = jnp.zeros((cin * self.mult,), jnp.float32)
        return p

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 1))
        y = jax.lax.conv_general_dilated(
            x, params["W"], window_strides=self.subsample,
            padding=self.border_mode.upper(),
            feature_group_count=x.shape[-1],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.bias:
            y = y + params["b"]
        if self.activation:
            y = self.activation(y)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            b, c, h, w = input_shape
        else:
            b, h, w, c = input_shape
        oh = _out_dim(h, self.kernel[0], self.subsample[0], self.border_mode)
        ow = _out_dim(w, self.kernel[1], self.subsample[1], self.border_mode)
        if self.dim_ordering == "th":
            return (b, c * self.mult, oh, ow)
        return (b, oh, ow, c * self.mult)
