"""Core keras-1 layers on JAX.

Rebuild of the reference's core layer set (Python wrappers
``pyzoo/zoo/pipeline/api/keras/layers/core.py``, Scala implementations
``zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/keras/layers/``).
Keras-1 argument names are preserved (``output_dim``, ``init``, ``W_regularizer``,
``bias``) so reference user code ports by changing the import line.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from zoo_tpu.pipeline.api.keras.engine.base import (
    KTensor,
    Layer,
    get_activation_fn,
    get_initializer,
    layer_rng,
    normalize_shape,
)


class InputLayer(Layer):
    """Placeholder layer (reference: ``core.py`` ``InputLayer``)."""

    def __init__(self, input_shape=None, **kwargs):
        super().__init__(input_shape=input_shape, **kwargs)

    def call(self, params, inputs, *, training=False, rng=None):
        return inputs


class Dense(Layer):
    """Fully-connected layer, keras-1 style (reference: Scala ``Dense.scala``,
    Python ``core.py`` ``Dense``). ``output_dim`` / ``init`` / ``bias``
    keyword names match the reference."""

    def __init__(self, output_dim: int, init="glorot_uniform",
                 activation=None, bias: bool = True,
                 W_regularizer=None, b_regularizer=None,
                 input_dim: Optional[int] = None, **kwargs):
        if input_dim is not None and kwargs.get("input_shape") is None:
            kwargs["input_shape"] = (input_dim,)
        super().__init__(**kwargs)
        self.output_dim = int(output_dim)
        self.init = get_initializer(init)
        self.activation = get_activation_fn(activation)
        self.bias = bias
        self.W_regularizer = W_regularizer
        self.b_regularizer = b_regularizer

    def build(self, rng, input_shape):
        in_dim = input_shape[-1]
        k_w, _ = jax.random.split(rng)
        params = {"W": self.init(k_w, (in_dim, self.output_dim), jnp.float32)}
        if self.bias:
            params["b"] = jnp.zeros((self.output_dim,), jnp.float32)
        return params

    def call(self, params, inputs, *, training=False, rng=None):
        if "W_q" in params:  # int8 weights (InferenceModel.quantize path)
            from zoo_tpu.ops.pallas.quant import quantized_dense
            y = quantized_dense(inputs, params["W_q"], params["W_scale"])
        else:
            y = jnp.matmul(inputs, params["W"])
        if self.bias:
            y = y + params["b"]
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class Activation(Layer):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self.activation = get_activation_fn(activation)

    def call(self, params, inputs, *, training=False, rng=None):
        return self.activation(inputs)


class Dropout(Layer):
    """Inverted dropout (reference: ``core.py`` ``Dropout``); identity at
    inference like the reference's BigDL Dropout."""

    def __init__(self, p: float, **kwargs):
        super().__init__(**kwargs)
        self.p = float(p)

    def call(self, params, inputs, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return inputs
        if rng is None:
            raise ValueError(f"{self.name}: dropout needs an rng in training")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(layer_rng(rng, self.name), keep,
                                    inputs.shape)
        return jnp.where(mask, inputs / keep, 0.0)


class Flatten(Layer):
    def call(self, params, inputs, *, training=False, rng=None):
        return inputs.reshape((inputs.shape[0], -1))

    def compute_output_shape(self, input_shape):
        return (input_shape[0], int(np.prod(input_shape[1:])))


class Reshape(Layer):
    """Reshape non-batch dims (reference: ``core.py`` ``Reshape``; supports
    one -1 wildcard)."""

    def __init__(self, target_shape: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.target_shape = tuple(target_shape)

    def _resolve(self, input_shape):
        in_elems = int(np.prod(input_shape[1:]))
        out = list(self.target_shape)
        if -1 in out:
            i = out.index(-1)
            known = int(np.prod([d for d in out if d != -1]))
            out[i] = in_elems // known
        return tuple(out)

    def call(self, params, inputs, *, training=False, rng=None):
        return inputs.reshape((inputs.shape[0],) + self._resolve(
            (None,) + inputs.shape[1:]))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self._resolve(input_shape)


class Permute(Layer):
    def __init__(self, dims: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.dims = tuple(dims)  # 1-indexed over non-batch dims (keras-1)

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.transpose(inputs, (0,) + self.dims)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + tuple(input_shape[d] for d in self.dims)


class RepeatVector(Layer):
    def __init__(self, n: int, **kwargs):
        super().__init__(**kwargs)
        self.n = int(n)

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.repeat(inputs[:, None, :], self.n, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.n, input_shape[1])


class GaussianNoise(Layer):
    def __init__(self, sigma: float, **kwargs):
        super().__init__(**kwargs)
        self.sigma = float(sigma)

    def call(self, params, inputs, *, training=False, rng=None):
        if not training or self.sigma <= 0:
            return inputs
        noise = jax.random.normal(layer_rng(rng, self.name), inputs.shape)
        return inputs + self.sigma * noise


class Lambda(Layer):
    """Wrap an arbitrary jax-traceable function (reference keras-1 Lambda /
    the autograd ``Lambda`` at ``autograd.py:472``)."""

    def __init__(self, function, output_shape=None, **kwargs):
        super().__init__(**kwargs)
        self.function = function
        self._output_shape = output_shape

    def call(self, params, inputs, *, training=False, rng=None):
        return self.function(inputs)

    def compute_output_shape(self, input_shape):
        if self._output_shape is not None:
            return normalize_shape(self._output_shape)
        # trace with ShapeDtypeStruct to infer
        single = not isinstance(input_shape, list)
        shapes = [input_shape] if single else input_shape
        args = [jax.ShapeDtypeStruct((1,) + tuple(s[1:]), jnp.float32)
                for s in shapes]
        out = jax.eval_shape(self.function, *(args if not single else args[:1]))
        return (None,) + tuple(out.shape[1:])


class Embedding(Layer):
    """Trainable lookup table (reference: ``embedding.py`` ``Embedding``,
    Scala ``Embedding.scala``). Input: int ids ``(batch, seq)`` or
    ``(batch,)``; output gains a trailing ``output_dim`` axis.

    TPU note: lookups lower to one-hot matmuls or dynamic-gathers on the MXU;
    keep vocab on-device (sharding of giant tables comes from the fsdp axis
    via the estimator's param sharding)."""

    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 input_length: Optional[int] = None, **kwargs):
        if input_length is not None and kwargs.get("input_shape") is None:
            kwargs["input_shape"] = (input_length,)
        super().__init__(**kwargs)
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.init = get_initializer(init)

    def build(self, rng, input_shape):
        return {"E": self.init(rng, (self.input_dim, self.output_dim),
                               jnp.float32)}

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.take(params["E"], inputs.astype(jnp.int32), axis=0)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class BatchNormalization(Layer):
    """Batch norm over the feature axis with running stats carried in params
    (reference: Scala ``BatchNormalization.scala``; keras-1 args).

    Running mean/var live in ``params["stats"]`` and are updated outside the
    gradient (stop_gradient) — the train step returns updated params, the
    eval path consumes them. ``mode``/``axis`` beyond keras-1 defaults are
    not supported."""

    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 beta_init="zero", gamma_init="one", **kwargs):
        super().__init__(**kwargs)
        self.epsilon = float(epsilon)
        self.momentum = float(momentum)
        self.beta_init = get_initializer(beta_init)
        self.gamma_init = get_initializer(gamma_init)

    def build(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        return {
            "gamma": self.gamma_init(k1, (d,), jnp.float32),
            "beta": self.beta_init(k2, (d,), jnp.float32),
            "stats": {"mean": jnp.zeros((d,), jnp.float32),
                      "var": jnp.ones((d,), jnp.float32)},
        }

    def call(self, params, inputs, *, training=False, rng=None):
        axes = tuple(range(inputs.ndim - 1))
        # f32 island for the STATS only (batch moments in bf16 destabilize
        # the normalization); the per-element normalize is then applied as
        # a precomputed (C,)-vector scale/shift in the compute dtype —
        # bf16 elementwise runs at twice the f32 vector rate and the big
        # activation tensor never round-trips through f32
        if training:
            xf = inputs.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
        else:
            mean, var = params["stats"]["mean"], params["stats"]["var"]
        inv = jax.lax.rsqrt(var + self.epsilon) \
            * params["gamma"].astype(jnp.float32)
        shift = params["beta"].astype(jnp.float32) - mean * inv
        return inputs * inv.astype(inputs.dtype) \
            + shift.astype(inputs.dtype)

    def updated_stats(self, params, inputs):
        axes = tuple(range(inputs.ndim - 1))
        xf = inputs.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        m = self.momentum
        return {
            "mean": m * params["stats"]["mean"] + (1 - m) * jax.lax.stop_gradient(mean),
            "var": m * params["stats"]["var"] + (1 - m) * jax.lax.stop_gradient(var),
        }


class Merge(Layer):
    """Merge a list of inputs (reference: ``core.py`` ``Merge`` /
    ``merge()``): modes concat / sum / mul / ave / max / min / dot / cos."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1, **kwargs):
        super().__init__(**kwargs)
        self.mode = mode
        self.concat_axis = concat_axis

    def call(self, params, inputs, *, training=False, rng=None):
        xs = inputs  # list of arrays
        if self.mode == "concat":
            return jnp.concatenate(xs, axis=self.concat_axis)
        if self.mode == "sum":
            return sum(xs)
        if self.mode == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if self.mode == "ave":
            return sum(xs) / len(xs)
        if self.mode == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if self.mode == "min":  # keras2 Minimum (keras2/layers/merge.py:62)
            out = xs[0]
            for x in xs[1:]:
                out = jnp.minimum(out, x)
            return out
        if self.mode == "dot":
            return jnp.sum(xs[0] * xs[1], axis=-1, keepdims=True)
        if self.mode == "cos":
            a, b = xs[0], xs[1]
            num = jnp.sum(a * b, axis=-1, keepdims=True)
            den = (jnp.linalg.norm(a, axis=-1, keepdims=True) *
                   jnp.linalg.norm(b, axis=-1, keepdims=True))
            return num / jnp.maximum(den, 1e-8)
        raise ValueError(f"unknown merge mode: {self.mode}")

    def compute_output_shape(self, input_shape):
        shapes = input_shape  # list
        if self.mode == "concat":
            ax = self.concat_axis
            out = list(shapes[0])
            dim = 0
            for s in shapes:
                if s[ax] is None:
                    dim = None
                    break
                dim += s[ax]
            out[ax] = dim
            return tuple(out)
        if self.mode in ("dot", "cos"):
            return (shapes[0][0], 1)
        return tuple(shapes[0])


def merge(inputs: Sequence[KTensor], mode: str = "sum", concat_axis: int = -1,
          name: Optional[str] = None) -> KTensor:
    """Functional-API merge helper (reference: ``core.py`` ``merge``)."""
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(list(inputs))
