"""Reference layer names without a distinct TPU-native mechanism
(``pyzoo/zoo/pipeline/api/keras/layers/core.py:365`` ``SparseDense``,
``embeddings.py:166`` ``SparseEmbedding``, ``torch.py:395`` ``Mul``,
``wrappers.py:86`` ``KerasLayerWrapper``). Sparse*: the JVM fabric used
sparse tensors to skip useless gradInput work; XLA consumes dense
minibatches, so these ARE Dense/Embedding with the reference's extra
arguments accepted (wide&deep-style callers keep working).
``KerasLayerWrapper`` adapts one tf.keras layer through the structural
keras bridge."""

from __future__ import annotations

import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import Layer
from zoo_tpu.pipeline.api.keras.layers.core import Dense, Embedding


class SparseDense(Dense):
    """reference ``SparseDense`` — Dense over (the densified form of) a
    sparse input; ``backward_start``/``backward_length`` gated partial
    backprop on the JVM and are accepted and ignored here (autodiff
    through a dense minibatch has no such cost cliff)."""

    def __init__(self, output_dim, backward_start=-1, backward_length=-1,
                 **kwargs):
        super().__init__(output_dim, **kwargs)


class SparseEmbedding(Embedding):
    """reference ``SparseEmbedding`` — Embedding whose JVM twin consumed
    SparseTensor ids; ids here are dense int arrays already."""


class Mul(Layer):
    """reference ``torch.py:395`` ``Mul`` — multiply the input by ONE
    learned scalar."""

    def build(self, rng, input_shape):
        return {"w": jnp.ones((1,), jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        return x * params["w"].astype(x.dtype)

    def compute_output_shape(self, input_shape):
        return input_shape


class KerasLayerWrapper:
    """reference ``wrappers.py:86`` — wrap a single tf.keras layer as a
    zoo layer by converting it through the structural keras bridge."""

    def __new__(cls, keras_layer, input_shape=None, **kwargs):
        import tensorflow as tf

        from zoo_tpu.bridges.keras_bridge import convert_keras_model

        if input_shape is None:
            raise ValueError("KerasLayerWrapper needs input_shape")
        km = tf.keras.Sequential(
            [tf.keras.Input(shape=tuple(input_shape)), keras_layer])
        zmodel = convert_keras_model(km)
        # a single-layer conversion yields one zoo layer; return it
        layers = getattr(zmodel, "layers", None)
        if layers and len(layers) == 1:
            return layers[0]
        return zmodel
