"""Element-wise / utility layers closing the keras-1 layer-zoo gap.

Rebuild of the reference's "torch-style" utility layers (Python
``pyzoo/zoo/pipeline/api/keras/layers/torch.py`` — AddConstant, MulConstant,
CAdd, CMul, Exp, Log, Sqrt, Square, Power, Negative, Identity, HardTanh,
HardShrink, SoftShrink, Threshold, BinaryThreshold, RReLU, Scale, Narrow,
Select, Squeeze, ExpandDim, Max, GetShape ... Scala
``pipeline/api/keras/layers/*.scala``), the noise layers
(``noise.py`` GaussianDropout / GaussianSampler), Masking (``core.py``),
LRN (``normalization.py``), ResizeBilinear and WordEmbedding
(``embeddings.py``). Each is a stateless jnp map — XLA fuses them into
neighbours, so there is no kernel cost to the fine granularity.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from zoo_tpu.pipeline.api.keras.engine.base import Layer, layer_rng


class _Elementwise(Layer):
    """Shape-preserving parameterless map."""

    def _fn(self, x):
        raise NotImplementedError

    def call(self, params, inputs, *, training=False, rng=None):
        return self._fn(inputs)


class Identity(_Elementwise):
    def _fn(self, x):
        return x


class Exp(_Elementwise):
    def _fn(self, x):
        return jnp.exp(x)


class Log(_Elementwise):
    def _fn(self, x):
        return jnp.log(x)


class Sqrt(_Elementwise):
    def _fn(self, x):
        return jnp.sqrt(x)


class Square(_Elementwise):
    def _fn(self, x):
        return jnp.square(x)


class Negative(_Elementwise):
    def _fn(self, x):
        return -x


class AddConstant(_Elementwise):
    def __init__(self, constant_scalar: float, **kwargs):
        super().__init__(**kwargs)
        self.c = float(constant_scalar)

    def _fn(self, x):
        return x + self.c


class MulConstant(_Elementwise):
    def __init__(self, constant_scalar: float, **kwargs):
        super().__init__(**kwargs)
        self.c = float(constant_scalar)

    def _fn(self, x):
        return x * self.c


class Power(_Elementwise):
    """reference: ``Power(power, scale, shift)`` → (shift + scale·x)^power."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.power, self.scale, self.shift = power, scale, shift

    def _fn(self, x):
        return jnp.power(self.shift + self.scale * x, self.power)


class HardTanh(_Elementwise):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.lo, self.hi = min_value, max_value

    def _fn(self, x):
        return jnp.clip(x, self.lo, self.hi)


class HardShrink(_Elementwise):
    def __init__(self, value: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.v = value

    def _fn(self, x):
        return jnp.where(jnp.abs(x) > self.v, x, 0.0)


class SoftShrink(_Elementwise):
    def __init__(self, value: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.v = value

    def _fn(self, x):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.v, 0.0)


class Threshold(_Elementwise):
    """x if x > th else value (reference: ``Threshold``)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.th, self.v = th, v

    def _fn(self, x):
        return jnp.where(x > self.th, x, self.v)


class BinaryThreshold(_Elementwise):
    def __init__(self, value: float = 1e-6, **kwargs):
        super().__init__(**kwargs)
        self.v = value

    def _fn(self, x):
        return (x > self.v).astype(jnp.float32)


class RReLU(Layer):
    """Randomized leaky ReLU: slope ~ U[lower, upper] in training, the
    midpoint at inference (reference: ``RReLU``)."""

    def __init__(self, lower: float = 1 / 8, upper: float = 1 / 3, **kwargs):
        super().__init__(**kwargs)
        self.lower, self.upper = lower, upper

    def call(self, params, inputs, *, training=False, rng=None):
        if training and rng is not None:
            r = layer_rng(rng, self.name)
            slope = jax.random.uniform(r, inputs.shape,
                                       minval=self.lower, maxval=self.upper)
        else:
            slope = (self.lower + self.upper) / 2.0
        return jnp.where(inputs >= 0, inputs, inputs * slope)


class CAdd(Layer):
    """Learnable per-element bias of shape ``size`` (reference: ``CAdd``)."""

    def __init__(self, size: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.size = tuple(int(s) for s in size)

    def build(self, rng, input_shape):
        return {"b": jnp.zeros(self.size, jnp.float32)}

    def call(self, params, inputs, *, training=False, rng=None):
        return inputs + params["b"]


class CMul(Layer):
    """Learnable per-element scale of shape ``size`` (reference: ``CMul``)."""

    def __init__(self, size: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.size = tuple(int(s) for s in size)

    def build(self, rng, input_shape):
        return {"g": jnp.ones(self.size, jnp.float32)}

    def call(self, params, inputs, *, training=False, rng=None):
        return inputs * params["g"]


class Scale(Layer):
    """CMul then CAdd (reference: ``Scale``)."""

    def __init__(self, size: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.size = tuple(int(s) for s in size)

    def build(self, rng, input_shape):
        return {"g": jnp.ones(self.size, jnp.float32),
                "b": jnp.zeros(self.size, jnp.float32)}

    def call(self, params, inputs, *, training=False, rng=None):
        return inputs * params["g"] + params["b"]


class Narrow(Layer):
    """Slice ``length`` elements from ``offset`` along ``dim`` (reference:
    ``Narrow``; dim counts the batch as 0, matching the reference)."""

    def __init__(self, dim: int, offset: int, length: int = 1, **kwargs):
        super().__init__(**kwargs)
        self.dim, self.offset, self.length = dim, offset, length

    def call(self, params, inputs, *, training=False, rng=None):
        ix = [slice(None)] * inputs.ndim
        length = self.length if self.length != -1 \
            else inputs.shape[self.dim] - self.offset
        ix[self.dim] = slice(self.offset, self.offset + length)
        return inputs[tuple(ix)]

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        if self.length != -1:
            out[self.dim] = self.length
        elif out[self.dim] is not None:
            out[self.dim] = out[self.dim] - self.offset
        return tuple(out)


class Select(Layer):
    """Pick index ``index`` along ``dim`` (reference: ``Select``)."""

    def __init__(self, dim: int, index: int, **kwargs):
        super().__init__(**kwargs)
        self.dim, self.index = dim, index

    def call(self, params, inputs, *, training=False, rng=None):
        return jax.lax.index_in_dim(inputs, self.index, axis=self.dim,
                                    keepdims=False)

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        del out[self.dim]
        return tuple(out)


class Squeeze(Layer):
    def __init__(self, dim: int, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.squeeze(inputs, axis=self.dim)

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        del out[self.dim]
        return tuple(out)


class ExpandDim(Layer):
    def __init__(self, dim: int, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.expand_dims(inputs, self.dim)

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        out.insert(self.dim if self.dim >= 0 else len(out) + 1 + self.dim,
                   1)
        return tuple(out)


class Max(Layer):
    """Max over ``dim`` (reference: ``Max(dim, return_value=True)``)."""

    def __init__(self, dim: int, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.max(inputs, axis=self.dim)

    def compute_output_shape(self, input_shape):
        out = list(input_shape)
        del out[self.dim]
        return tuple(out)


class GetShape(Layer):
    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.asarray(inputs.shape, jnp.int32)

    def compute_output_shape(self, input_shape):
        return (len(input_shape),)


class Masking(Layer):
    """Zero out timesteps equal to ``mask_value`` everywhere (reference:
    ``Masking``; downstream zoo RNNs see zeroed steps rather than a mask
    tensor — matching the BigDL implementation's effect on padded data)."""

    def __init__(self, mask_value: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.mask_value = mask_value

    def call(self, params, inputs, *, training=False, rng=None):
        keep = jnp.any(inputs != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, inputs, 0.0)


class GaussianDropout(Layer):
    """Multiplicative 1-mean gaussian noise (reference: ``noise.py``)."""

    def __init__(self, p: float, **kwargs):
        super().__init__(**kwargs)
        self.p = float(p)

    def call(self, params, inputs, *, training=False, rng=None):
        if not training or rng is None or self.p <= 0:
            return inputs
        std = np.sqrt(self.p / (1.0 - self.p))
        r = layer_rng(rng, self.name)
        return inputs * (1.0 + std * jax.random.normal(r, inputs.shape))


class GaussianSampler(Layer):
    """Sample from N(mean, exp(log_var/2)) given ``[mean, log_var]`` — the
    VAE reparameterization (reference: ``GaussianSampler``)."""

    def call(self, params, inputs, *, training=False, rng=None):
        mean, log_var = inputs
        if rng is None:
            return mean
        r = layer_rng(rng, self.name)
        return mean + jnp.exp(log_var * 0.5) * \
            jax.random.normal(r, mean.shape)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[0])


class LRN2D(Layer):
    """Local response normalization across channels (reference: ``LRN2D``;
    AlexNet-era). ``dim_ordering`` handled like the conv layers."""

    def __init__(self, alpha: float = 1e-4, k: float = 1.0, beta: float = 0.75,
                 n: int = 5, dim_ordering: str = "th", **kwargs):
        super().__init__(**kwargs)
        self.alpha, self.k, self.beta, self.n = alpha, k, beta, int(n)
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        caxis = 1 if self.dim_ordering == "th" else 3
        sq = jnp.square(x)
        half = self.n // 2
        # sum sq over a window of n channels
        pads = [(0, 0)] * x.ndim
        pads[caxis] = (half, half)
        padded = jnp.pad(sq, pads)
        acc = sum(
            jax.lax.slice_in_dim(padded, i, i + x.shape[caxis], axis=caxis)
            for i in range(self.n))
        return x / jnp.power(self.k + self.alpha / self.n * acc, self.beta)


class WithinChannelLRN2D(Layer):
    """LRN over a spatial window within each channel (reference:
    ``WithinChannelLRN2D``)."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, **kwargs):
        super().__init__(**kwargs)
        self.size, self.alpha, self.beta = int(size), alpha, beta

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs  # (B, C, H, W) th-style per reference
        sq = jnp.square(x)
        half = self.size // 2
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, (1, 1, self.size, self.size),
            (1, 1, 1, 1),
            ((0, 0), (0, 0), (half, half), (half, half)))
        denom = jnp.power(1.0 + self.alpha / (self.size ** 2) * summed,
                          self.beta)
        return x / denom


class ResizeBilinear(Layer):
    """Bilinear resize of the spatial dims (reference: ``ResizeBilinear``)."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, dim_ordering: str = "th",
                 **kwargs):
        super().__init__(**kwargs)
        self.oh, self.ow = int(output_height), int(output_width)
        self.align_corners = align_corners
        self.dim_ordering = dim_ordering

    def call(self, params, inputs, *, training=False, rng=None):
        x = inputs
        if self.dim_ordering == "th":
            x = jnp.transpose(x, (0, 2, 3, 1))
        b, h, w, c = x.shape
        method = "bilinear"
        y = jax.image.resize(x, (b, self.oh, self.ow, c), method=method)
        if self.dim_ordering == "th":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            return (input_shape[0], input_shape[1], self.oh, self.ow)
        return (input_shape[0], self.oh, self.ow, input_shape[3])
