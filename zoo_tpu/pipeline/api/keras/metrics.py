"""Validation metrics (reference: Python ``keras/metrics.py`` +
Orca ``orca/learn/metrics.py:19-340`` + Scala ``keras/metrics/AUC.scala``).

Each metric is a pure batch function ``f(y_true, y_pred) -> (value_sum,
count)`` so the engine can aggregate exactly across batches and data-parallel
shards (sum both, divide at the end) — the same contract the reference's
BigDL ValidationMethods implement JVM-side.
"""

from __future__ import annotations

from typing import Callable, Union

import jax.numpy as jnp


class Metric:
    name = "metric"

    def batch_eval(self, y_true, y_pred):
        """Return (sum, count) contributions for this batch."""
        raise NotImplementedError

    def finalize(self, total, count):
        return total / jnp.maximum(count, 1)


class Accuracy(Metric):
    """Classification accuracy; auto-detects binary (prob scalar output) vs
    categorical (argmax) like the reference's ``Accuracy`` validation method.
    """

    name = "accuracy"

    def batch_eval(self, y_true, y_pred):
        if y_pred.ndim <= 1 or y_pred.shape[-1] == 1:
            pred = (y_pred.reshape(y_pred.shape[0], -1)[:, 0] > 0.5)
            true = y_true.reshape(y_true.shape[0], -1)[:, 0] > 0.5
        else:
            pred = jnp.argmax(y_pred, axis=-1)
            true = (jnp.argmax(y_true, axis=-1)
                    if y_true.ndim == y_pred.ndim else
                    y_true.reshape(pred.shape).astype(jnp.int32))
        correct = jnp.sum((pred == true).astype(jnp.float32))
        return correct, jnp.asarray(pred.shape[0], jnp.float32)


class SparseCategoricalAccuracy(Accuracy):
    name = "sparse_categorical_accuracy"


class Top5Accuracy(Metric):
    name = "top5_accuracy"

    def batch_eval(self, y_true, y_pred):
        top5 = jnp.argsort(y_pred, axis=-1)[:, -5:]
        true = (jnp.argmax(y_true, axis=-1) if y_true.ndim == y_pred.ndim
                else y_true.astype(jnp.int32).reshape(-1))
        hit = jnp.any(top5 == true[:, None], axis=-1)
        return jnp.sum(hit.astype(jnp.float32)), jnp.asarray(
            y_pred.shape[0], jnp.float32)


class MAE(Metric):
    name = "mae"

    def batch_eval(self, y_true, y_pred):
        return (jnp.sum(jnp.abs(y_pred - y_true)),
                jnp.asarray(y_true.size, jnp.float32))


class MSE(Metric):
    name = "mse"

    def batch_eval(self, y_true, y_pred):
        return (jnp.sum((y_pred - y_true) ** 2),
                jnp.asarray(y_true.size, jnp.float32))


class BinaryCrossEntropyMetric(Metric):
    name = "binary_crossentropy"

    def batch_eval(self, y_true, y_pred):
        eps = 1e-7
        p = jnp.clip(y_pred, eps, 1 - eps)
        ll = y_true * jnp.log(p) + (1 - y_true) * jnp.log(1 - p)
        return -jnp.sum(ll), jnp.asarray(y_true.size, jnp.float32)


class AUC(Metric):
    """Riemann-sum AUC over fixed thresholds, jittable and exactly mergeable
    across batches (reference: native ``keras/metrics/AUC.scala:211LoC`` uses
    the same thresholded-confusion-matrix construction)."""

    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.num_thresholds = int(num_thresholds)

    def batch_eval(self, y_true, y_pred):
        t = jnp.linspace(0.0, 1.0, self.num_thresholds)
        p = y_pred.reshape(-1)
        y = y_true.reshape(-1)
        pred_pos = p[None, :] >= t[:, None]          # (T, N)
        tp = jnp.sum(pred_pos & (y[None, :] > 0.5), axis=1).astype(jnp.float32)
        fp = jnp.sum(pred_pos & (y[None, :] <= 0.5), axis=1).astype(jnp.float32)
        pos = jnp.sum(y > 0.5).astype(jnp.float32)
        neg = jnp.sum(y <= 0.5).astype(jnp.float32)
        # carry the confusion-matrix rows; finalize integrates
        return jnp.stack([tp, fp,
                          jnp.full_like(tp, pos), jnp.full_like(fp, neg)]), \
            jnp.asarray(1.0, jnp.float32)

    def finalize(self, total, count):
        tp, fp, pos, neg = total[0], total[1], total[2], total[3]
        tpr = tp / jnp.maximum(pos, 1.0)
        fpr = fp / jnp.maximum(neg, 1.0)
        # integrate TPR over FPR (thresholds descend in fpr ordering)
        order = jnp.argsort(fpr)
        fpr, tpr = fpr[order], tpr[order]
        return jnp.trapezoid(tpr, fpr)


_ALIASES = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "sparse_categorical_accuracy": SparseCategoricalAccuracy,
    "top5accuracy": Top5Accuracy,
    "top5_accuracy": Top5Accuracy,
    "mae": MAE,
    "mse": MSE,
    "auc": AUC,
    "binary_crossentropy": BinaryCrossEntropyMetric,
}


def get_metric(identifier: Union[str, Metric]) -> Metric:
    if isinstance(identifier, Metric):
        return identifier
    key = identifier.lower()
    if key not in _ALIASES:
        raise ValueError(f"unknown metric: {identifier}")
    return _ALIASES[key]()


# reference validation-method names (``orca/learn/metrics.py:19-340``
# compiled Metric classes to these BigDL ValidationMethods)
Top1Accuracy = Accuracy
