from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model, Sequential

__all__ = ["Input", "Model", "Sequential"]
