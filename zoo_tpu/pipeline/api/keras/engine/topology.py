"""Sequential / functional Model with compile/fit/evaluate/predict.

Rebuild of the reference's ``KerasNet`` (Scala
``pipeline/api/keras/models/Topology.scala:139,347,504`` — compile/fit/
evaluate/predict over FeatureSet + InternalDistriOptimizer) and the Python
facade ``pyzoo/zoo/pipeline/api/keras/engine/topology.py``.

The TPU re-architecture collapses the reference's per-iteration "2 Spark
jobs + JNI weight push/pull + PS-shuffle allreduce" (``Topology.scala:1262``,
``wp-bigdl.md:146-160``) into ONE jitted XLA computation per step: forward,
backward, gradient allreduce over the mesh ``data`` axes, and the optimizer
update are fused and scheduled by XLA; weights never leave the device.
"""

from __future__ import annotations

import contextlib
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from zoo_tpu.common.context import get_runtime_context
from zoo_tpu.obs.metrics import counter as _obs_counter
from zoo_tpu.pipeline.api.keras.engine.base import KTensor, Layer
from zoo_tpu.pipeline.api.keras.engine import data_utils
from zoo_tpu.pipeline.api.keras.metrics import Metric, get_metric
from zoo_tpu.pipeline.api.keras.objectives import get_loss
from zoo_tpu.pipeline.api.keras.optimizers import get_optimizer


def _split_state(params: Dict) -> Tuple[Dict, Dict]:
    """Separate non-trainable running stats (e.g. BatchNorm) from trainable
    params so grads are only taken w.r.t. the latter."""
    trainable, state = {}, {}
    for lname, p in params.items():
        if isinstance(p, dict) and "stats" in p:
            state[lname] = {"stats": p["stats"]}
            trainable[lname] = {k: v for k, v in p.items() if k != "stats"}
        else:
            trainable[lname] = p
    return trainable, state


def _merge_state(trainable: Dict, state: Dict) -> Dict:
    out = dict(trainable)
    for lname, st in state.items():
        merged = dict(out.get(lname, {}))
        merged.update(st)
        out[lname] = merged
    return out


#: params key the pipeline plan stacks the homogeneous layer run under
#: (same literal as ``zoo_tpu.parallel.plans.PIPE_BODY_KEY``; kept
#: inline so _forward tracing never imports the plans module)
_PIPE_BODY_KEY = "__pipe_body__"

# Event-file-backed summaries (own writer + disk read-back) live in
# zoo_tpu.tensorboard; re-exported here for the keras facade.
from zoo_tpu.tensorboard import TrainSummary  # noqa: E402

_collective_bytes = _obs_counter(
    "zoo_mesh_collective_bytes_total",
    "Estimated per-step collective traffic the active sharding plan "
    "implies, accumulated over executed train steps (static plan "
    "estimate — fsdp weight gathers + grad reductions; see "
    "zoo_tpu.parallel.plans.estimate_collective_bytes)",
    labels=("op",))

# serializes lazy jit-cache builds: concurrent first predicts (the
# multi-replica ServingServer batcher threads) could otherwise each
# build a PRIVATE jit object for the same step fn — two full XLA
# compiles of the same executable, a multi-second p99 spike per extra
# thread on TPU. Module-level (not an instance attr) so models stay
# cloudpickle-serializable.
_JIT_BUILD_LOCK = threading.Lock()


def _scan_steps(step, params, opt_state, rng, stacked):
    """``lax.scan`` of the train step over batches stacked as
    (k, batch, ...); the shared core of the multi-step and whole-epoch
    dispatch paths — their per-step math must stay identical."""
    def body(carry, batch):
        p, o, r = carry
        p, o, r, loss = step(p, o, r, *batch)
        return (p, o, r), loss

    (params, opt_state, rng), losses = jax.lax.scan(
        body, (params, opt_state, rng), stacked)
    return params, opt_state, rng, jnp.sum(losses)


class KerasNet:
    """Shared training engine for Sequential and Model."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or type(self).__name__.lower()
        self.params: Optional[Dict] = None
        self.optimizer = None
        self.loss_fn: Optional[Callable] = None
        self.metrics: List[Metric] = []
        self._opt_state = None
        self._step = 0
        self.train_summary = TrainSummary()
        self.validation_summary = TrainSummary()
        self._jit_train = None
        self._jit_multi = None
        self._jit_eval = None
        self._jit_pred = None
        self._built_shapes: Optional[List[Tuple]] = None
        self._grad_clip: Optional[Tuple] = None
        self._guard = None  # TrainingGuard (orca/learn/guard.py)

    # -- param keys --------------------------------------------------------
    def _param_keys(self) -> Dict[int, str]:
        """Deterministic params keys by layer position+type (NOT the
        process-global auto names) so checkpoints restore into fresh model
        instances — the reference gets this for free from its Scala module
        serialization; position-keying is our equivalent."""
        return {id(layer): f"{i:03d}_{type(layer).__name__.lower()}"
                for i, layer in enumerate(self.layers)}

    def _key_of(self, layer) -> str:
        return self._param_keys()[id(layer)]

    # -- to be provided by subclasses ------------------------------------
    def _init_params(self, rng, input_shapes) -> Dict:
        raise NotImplementedError

    def _forward(self, params, inputs: List, *, training: bool, rng,
                 collect: Optional[Dict]):
        raise NotImplementedError

    def _input_shapes(self) -> Optional[List[Tuple]]:
        raise NotImplementedError

    @property
    def layers(self) -> List[Layer]:
        raise NotImplementedError

    # -- public API (keras-1 names, reference Topology.scala) -------------
    def compile(self, optimizer, loss, metrics=None,
                loss_weights=None, dtype_policy: str = "float32",
                plan: Optional[str] = None):
        """reference: ``KerasNet.compile`` ``Topology.scala:139``.

        ``loss_weights``: optional per-output scalar weights for
        multi-output models (keras semantics; reference multi-task use).

        ``dtype_policy``: "float32" (default) or "mixed_bfloat16" — params
        and optimizer state stay f32, forward/backward compute runs in
        bf16 on the MXU with f32 islands in the normalizations/softmax
        (net-new: the reference's fabric is f32-only CPU).

        ``plan``: sharding plan for every placement/step this model
        makes (``zoo_tpu.parallel.plans`` registry; default env
        ``ZOO_PLAN`` → ``"auto"``). ``"pipeline"`` additionally
        restructures the params tree: the longest homogeneous layer run
        stacks into one stage-stacked body the GPipe microbatch
        schedule consumes (guard counters / rng / loss stay replicated
        exactly as every other plan, so guard/checkpoint/preemption
        inherit unchanged)."""
        if dtype_policy not in ("float32", "mixed_bfloat16"):
            raise ValueError(f"unknown dtype_policy: {dtype_policy}")
        from zoo_tpu.common import knobs as _knobs
        plan = plan or _knobs.value("ZOO_PLAN")
        if plan != "auto":
            from zoo_tpu.parallel.plans import get_plan
            get_plan(plan)  # unknown plan names fail here, not mid-fit
        self._plan = plan
        if plan == "pipeline" and self.params is not None:
            self.params = self._stack_pipe_body(self.params)
        n_out = len(getattr(self, "outputs", [None]))
        if n_out > 1 and not isinstance(loss, (list, tuple)):
            raise ValueError(
                f"model has {n_out} outputs; compile(loss=[...]) needs one "
                "loss per output")
        if isinstance(loss, (list, tuple)) and len(loss) != n_out:
            raise ValueError(f"{len(loss)} losses for {n_out} outputs")
        if loss_weights is not None and not isinstance(loss,
                                                       (list, tuple)):
            raise ValueError("loss_weights needs a list of losses")
        self.dtype_policy = dtype_policy
        self.optimizer = get_optimizer(optimizer)
        if isinstance(loss, (list, tuple)):
            # multi-output: one loss per output, weighted sum (the
            # reference's multi-task graphs combine per-head criteria the
            # same way)
            fns = [get_loss(l) for l in loss]
            ws = ([float(w) for w in loss_weights]
                  if loss_weights is not None else [1.0] * len(fns))
            if len(ws) != len(fns):
                raise ValueError(f"{len(ws)} loss_weights for "
                                 f"{len(fns)} losses")

            def _multi_loss(ys, preds):
                ys = ys if isinstance(ys, (list, tuple)) else [ys]
                preds = preds if isinstance(preds, (list, tuple)) else [preds]
                if not (len(fns) == len(ys) == len(preds)):
                    raise ValueError(
                        f"{len(fns)} losses, {len(ys)} label sets, "
                        f"{len(preds)} outputs — counts must match")
                return sum(w * f(y, p)
                           for w, f, y, p in zip(ws, fns, ys, preds))

            self.loss_fn = _multi_loss
            self.loss_name = "multi"
        else:
            self.loss_fn = get_loss(loss)
            self.loss_name = (loss if isinstance(loss, str)
                              else getattr(loss, "__name__", None))
        self.metrics = [get_metric(m) for m in (metrics or [])]
        self._jit_train = self._jit_eval = self._jit_pred = None
        self._jit_multi = self._own_jit_train = None
        self._jit_epoch_cache = None
        self._opt_state = None  # a new optimizer cannot reuse old state
        return self

    def _cast_compute(self, tree):
        """Cast float32 leaves to the compute dtype under the policy."""
        if getattr(self, "dtype_policy", "float32") != "mixed_bfloat16":
            return tree
        return jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if hasattr(a, "dtype") and a.dtype == jnp.float32 else a, tree)

    # -- gradient clipping (reference: Scala ``Estimator.scala:68`` area —
    # constant + L2-norm clipping applied inside DistriOptimizer) ----------
    def _drop_train_caches(self):
        """Invalidate every cache holding a traced train step — required
        whenever something the step closure bakes in changes (grad clip,
        loss, a layer-mode flag like seq2seq's train_self_feed)."""
        self._jit_train = self._jit_multi = self._own_jit_train = None
        self._jit_epoch_cache = None

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float):
        """Clip every gradient element into [min_value, max_value]."""
        self._grad_clip = ("const", float(min_value), float(max_value))
        # clip is in the step: drop every cache holding a traced step
        self._drop_train_caches()
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float):
        """Scale gradients so their global L2 norm is at most clip_norm."""
        self._grad_clip = ("l2", float(clip_norm))
        self._drop_train_caches()
        return self

    def clear_gradient_clipping(self):
        self._grad_clip = None
        self._drop_train_caches()
        return self

    def _apply_grad_clip(self, grads):
        """Applied to raw grads before the optimizer update — outside the
        optax chain so toggling clipping never invalidates optimizer state."""
        if self._grad_clip is None:
            return grads
        if self._grad_clip[0] == "const":
            _, lo, hi = self._grad_clip
            return jax.tree_util.tree_map(
                lambda g: jnp.clip(g, lo, hi), grads)
        (_, norm) = self._grad_clip
        import optax
        gnorm = optax.global_norm(grads)
        scale = jnp.minimum(1.0, norm / (gnorm + 1e-12))
        return jax.tree_util.tree_map(lambda g: g * scale, grads)

    def set_guard(self, guard):
        """Attach a :class:`zoo_tpu.orca.learn.guard.TrainingGuard`. The
        guard changes the traced step (health fold + device counters in
        the optimizer-state carry), so every train-step cache drops —
        attach once, before training, like the estimators do."""
        self._guard = guard
        self._drop_train_caches()
        return self

    def clear_guard(self):
        self._guard = None
        self._drop_train_caches()
        return self

    def _active_guard(self):
        g = getattr(self, "_guard", None)
        return g if g is not None and g.active else None

    def set_tensorboard(self, log_dir: str, app_name: str):
        """reference: ``Topology.scala:162-168``."""
        self.train_summary = TrainSummary(log_dir, app_name + "/train")
        self.validation_summary = TrainSummary(log_dir, app_name + "/val")

    def set_profile(self, trace_dir: Optional[str] = None,
                    trace_epochs: int = 1):
        """Enable per-phase step timers for the next ``fit`` (data-wait /
        device-step avg-ms scalars into the train summary) and, when
        ``trace_dir`` is given, an XLA profiler capture of the first
        ``trace_epochs`` epochs (rebuild of SURVEY §5.1; per-stage
        ``Timer.scala`` + net-new ``jax.profiler`` depth). Forces a
        device sync per step while enabled (accurate step times at the
        cost of dispatch overlap); ``clear_profile()`` turns it off."""
        from zoo_tpu.common.profiling import StepProfiler
        self._profiler = StepProfiler(trace_dir=trace_dir,
                                      trace_epochs=trace_epochs)
        return self._profiler

    def clear_profile(self):
        self._profiler = None

    def get_profile_stats(self):
        prof = getattr(self, "_profiler", None)
        return prof.stats() if prof else {}

    def get_train_summary(self, tag: str = "Loss"):
        return self.train_summary.read_scalar(tag)

    def get_validation_summary(self, tag: str):
        return self.validation_summary.read_scalar(tag)

    def build(self, rng=None, input_shapes=None):
        """Materialize params (idempotent)."""
        if self.params is not None:
            return self.params
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        shapes = input_shapes or self._input_shapes()
        if shapes is None:
            raise ValueError(
                f"{self.name}: cannot infer input shape; pass input_shape to "
                "the first layer or call build(input_shapes=...)")
        self._built_shapes = [tuple(s) for s in shapes]
        self.params = self._init_params(rng, shapes)
        if self._plan_name() == "pipeline":
            self.params = self._stack_pipe_body(self.params)
        return self.params

    def _n_inputs(self) -> int:
        shapes = self._built_shapes or self._input_shapes()
        return len(shapes) if shapes else 1

    # -- devices / sharding ----------------------------------------------
    def _mesh(self):
        ctx = get_runtime_context(required=False)
        return ctx.mesh if ctx is not None else None

    def _plan_name(self) -> str:
        """The sharding plan ``compile(plan=...)`` pinned (``"auto"``
        before compile / on models from old pickles)."""
        return getattr(self, "_plan", "auto")

    def _place(self, params):
        """Place params per the mesh plan: replicated across ``data``,
        ZeRO-sharded across ``fsdp``, tensor-parallel across ``model``,
        stage/expert-sharded across ``pipe``/``expert`` under the
        pipeline/moe plans (see ``zoo_tpu.parallel.plans``)."""
        from zoo_tpu.parallel.plans import place_params
        return place_params(params, self._mesh(), self._plan_name())

    # -- pipeline plan (GPipe body) ---------------------------------------
    def _stack_pipe_body(self, params):
        raise ValueError(
            "plan='pipeline' needs a Sequential model (got "
            f"{type(self).__name__}: no unambiguous layer chain to "
            "stage)")

    def _pipe_microbatches(self, stages: int) -> int:
        """GPipe microbatch count: ``ZOO_PIPE_MICROBATCHES`` (> 0) or
        one microbatch per stage."""
        from zoo_tpu.common import knobs as _knobs
        m = int(_knobs.value("ZOO_PIPE_MICROBATCHES") or 0)
        return m if m > 0 else stages

    def _apply_pipe_body(self, body, h, *, training):
        """Apply the stage-stacked homogeneous body: the GPipe
        microbatch schedule over the ``pipe`` mesh axis when training on
        one, a plain ``lax.scan`` over the layer stack otherwise — the
        same layer-by-layer math either way."""
        tmpl = self._pipe_template

        def step(carry, leaf_slice):
            return tmpl.call(leaf_slice, carry, training=training,
                             rng=None), None

        mesh = self._mesh()
        pipe = mesh.shape.get("pipe", 1) if mesh is not None else 1
        if training and pipe > 1:
            from zoo_tpu.parallel.pipeline import (
                pipeline_apply,
                stack_stages,
            )
            stages = stack_stages(body, pipe)

            def stage_fn(p_slice, hh):
                out, _ = jax.lax.scan(step, hh, p_slice)
                return out

            return pipeline_apply(stage_fn, stages, h, mesh,
                                  self._pipe_microbatches(pipe))
        out, _ = jax.lax.scan(step, h, body)
        return out

    def _put_batch(self, arrs: List[np.ndarray]):
        mesh = self._mesh()
        if mesh is None:
            return [jnp.asarray(a) for a in arrs]
        from zoo_tpu.parallel.mesh import batch_sharding, host_local_to_global
        if jax.process_count() > 1:
            # multi-host: each process contributes its local rows of the
            # global batch — assembled without any driver-side collect
            # (SURVEY §7.4 hard part #1; reference: ray_xshards.py locality)
            return [host_local_to_global(mesh,
                                         batch_sharding(mesh, a.ndim).spec,
                                         np.asarray(a)) for a in arrs]
        return [jax.device_put(a, batch_sharding(mesh, a.ndim)) for a in arrs]

    def _put_stacked(self, arrs: List):
        """Place (k, batch, ...) superbatches for the scanned multi-step:
        scan dim replicated, batch dim sharded over the data axes."""
        mesh = self._mesh()
        if mesh is None:
            return [jnp.asarray(a) for a in arrs]
        from zoo_tpu.parallel.mesh import stacked_batch_sharding
        return [jax.device_put(a, stacked_batch_sharding(mesh, a.ndim))
                for a in arrs]

    def _adapt_inputs(self, xs: List[np.ndarray]) -> List[np.ndarray]:
        """Single-input model fed k feature columns → stack into one
        (batch, k) tensor (the reference's NNEstimator assembles feature
        cols the same way via SeqToTensor, ``feature/common.py:94``)."""
        shapes = self._input_shapes() or self._built_shapes
        if shapes and len(shapes) == 1 and len(xs) > 1 \
                and all(a.ndim == 1 for a in xs):
            return [np.stack(xs, axis=1)]
        return xs

    # -- jitted steps -----------------------------------------------------
    def _make_step_fn(self):
        tx = self.optimizer.make()
        n_inputs = self._n_inputs()
        guard = self._active_guard()

        def step(params, opt_state, rng, *batch):
            # rng advances inside the jitted step — a host-side split per
            # step would be an extra dispatch (and a real cost when the
            # device sits behind a high-latency transport)
            if guard is not None:
                # the guard's device counters ride the opt-state carry so
                # the step keeps its (params, opt_state, rng, *batch)
                # signature through scan/jit/donation unchanged
                opt_state, gstate = opt_state
            step_rng, new_rng = jax.random.split(rng)
            xs = list(batch[:n_inputs])
            labels = list(batch[n_inputs:])
            ys = labels[0] if len(labels) == 1 else labels
            trainable, state = _split_state(params)

            def loss_fn(tr):
                collect = {}
                # cast trainables only: running stats (BatchNorm EMA) must
                # keep f32 resolution or momentum-0.99 increments vanish
                # below a bf16 ulp
                preds = self._forward(
                    _merge_state(self._cast_compute(tr), state),
                    self._cast_compute(xs), training=True, rng=step_rng,
                    collect=collect)
                if not getattr(self.loss_fn, "_handles_low_precision",
                               False):
                    preds = jax.tree.map(
                        lambda p: p.astype(jnp.float32)
                        if hasattr(p, "dtype") and p.dtype == jnp.bfloat16
                        else p, preds)
                return self.loss_fn(ys, preds), collect

            (loss, collect), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(trainable)
            grads = self._apply_grad_clip(grads)

            def _update(tr, opt, g):
                if getattr(self.optimizer, "fused", False):
                    # direct-apply path: the Pallas fused kernel writes
                    # new params in one pass, no optax updates/apply
                    # round trip
                    return self.optimizer.apply_fused(g, opt, tr)
                upd, opt = tx.update(g, opt, tr)
                import optax
                return optax.apply_updates(tr, upd), opt

            if guard is not None:
                # in-step health guard: the whole optimizer update runs
                # under lax.cond — a non-finite loss/grad-norm takes the
                # identity branch, so params, opt state and running
                # stats pass through UNCHANGED (buffers forwarded; no
                # host sync, and good steps pay only the norm reduce)
                ok = guard.grad_norm_ok(loss, grads)

                def _good(op):
                    tr, opt = _update(op[0], op[1], op[2])
                    return tr, opt, op[3]

                def _skip(op):
                    return op[0], op[1], state

                trainable, opt_state, new_stats = jax.lax.cond(
                    ok, _good, _skip,
                    (trainable, opt_state, grads, collect or state))
                gstate = guard.gstate_update(gstate, ok)
                loss = jnp.where(ok, loss, 0.0)
                return (_merge_state(trainable, new_stats),
                        (opt_state, gstate), new_rng, loss)
            trainable, opt_state = _update(trainable, opt_state, grads)
            new_params = _merge_state(trainable, collect or state)
            return new_params, opt_state, new_rng, loss

        return step

    # -- explicit GSPMD shardings (docs/multichip.md) ---------------------
    # On a >1-device mesh the train step is jitted with explicit
    # NamedSharding in/out shardings instead of relying on committed-input
    # inference: params/opt-state follow the placement plan
    # (zoo_tpu.parallel.plans — replicated over `data`, ZeRO-sharded over
    # `fsdp`, tensor-parallel over `model`), batches ride the data axes,
    # rng/loss and the guard's device counters are replicated. Explicit
    # out_shardings pin the updated params to the SAME layout, so a plan
    # regression cannot silently come back replicated (the hlo_check
    # FSDP lint asserts the same thing from the compiled text).
    def _state_shardings(self, params, opt_state):
        """(params_shardings, opt_state_shardings, replicated) for the
        current mesh, from the live placed arrays; None off-mesh."""
        mesh = self._mesh()
        if mesh is None or mesh.size <= 1:
            return None
        from zoo_tpu.parallel.mesh import replicated_sharding
        from zoo_tpu.parallel.plans import shardings_of
        return (shardings_of(params, mesh), shardings_of(opt_state, mesh),
                replicated_sharding(mesh))

    def _step_shardings(self, shard, batch_ndims, stacked: bool):
        """jit (in_shardings, out_shardings) for the train-step seam."""
        if shard is None:
            return None
        mesh = self._mesh()
        from zoo_tpu.parallel.mesh import (
            batch_sharding,
            stacked_batch_sharding,
        )
        p_sh, o_sh, rep = shard
        bfn = stacked_batch_sharding if stacked else batch_sharding
        ins = (p_sh, o_sh, rep) + tuple(
            bfn(mesh, nd + (1 if stacked else 0)) for nd in batch_ndims)
        return ins, (p_sh, o_sh, rep, rep)

    def _jit_step(self, fn, shardings):
        if shardings is None:
            return jax.jit(fn, donate_argnums=(0, 1, 2))
        ins, outs = shardings
        return jax.jit(fn, donate_argnums=(0, 1, 2),
                       in_shardings=ins, out_shardings=outs)

    def _build_train_step(self, shardings=None):
        return self._jit_step(self._make_step_fn(), shardings)

    def _build_multi_train_step(self, shardings=None):
        """K training steps per dispatch: ``lax.scan`` of the step over
        batches stacked as (k, batch, ...). One XLA execution covers k
        steps, amortizing per-call dispatch latency — the difference is
        decisive on high-latency PJRT transports (~tens of ms per call on
        a tunneled chip) and it is the TPU-native idiom regardless (the
        device runs autonomously instead of waiting on the host). The
        per-step math is IDENTICAL to the single-step path (same step
        function, scanned)."""
        step = self._make_step_fn()

        def multi(params, opt_state, rng, *stacked):
            return _scan_steps(step, params, opt_state, rng, stacked)

        return self._jit_step(multi, shardings)

    def _build_epoch_train_step(self, k: int, bs: int, gather: bool,
                                shard=None):
        """A FULL epoch in one dispatch: permutation-gather of the (small,
        device-resident) dataset + ``lax.scan`` of the step over all ``k``
        batches, inside a single jit call. On high-latency PJRT transports
        the per-dispatch overhead (measured 76-137ms per call on the
        tunneled dev chip) otherwise dominates small-model epochs — two
        superbatch dispatches cost more than the whole NCF epoch's
        compute. Only used for datasets small enough that the permuted
        gather copy is cheap (fit caps it at 256MB)."""
        step = self._make_step_fn()
        mesh = self._mesh()

        def epoch_fn(params, opt_state, rng, *args):
            if gather:
                *arrs, perm = args
                stacked = [a[perm].reshape((k, bs) + a.shape[1:])
                           for a in arrs]
            else:
                # shuffle=False: an identity gather would copy the whole
                # dataset in HBM for nothing — reshape is free
                stacked = [a[:k * bs].reshape((k, bs) + a.shape[1:])
                           for a in args]
            if mesh is not None and mesh.size > 1:
                # multi-device: pin the per-step batch dim onto the data
                # axes (the _put_stacked layout) so the scanned steps run
                # sharded instead of replicated
                from zoo_tpu.parallel.mesh import stacked_batch_sharding
                stacked = [jax.lax.with_sharding_constraint(
                    a, stacked_batch_sharding(mesh, a.ndim))
                    for a in stacked]
            return _scan_steps(step, params, opt_state, rng, stacked)

        if shard is not None:
            # dataset operands keep their resident placement (the gather
            # re-pins batches via the constraint above); the carried
            # params/opt-state come back pinned to the plan's layout
            p_sh, o_sh, rep = shard
            return jax.jit(epoch_fn, donate_argnums=(0, 1, 2),
                           out_shardings=(p_sh, o_sh, rep, rep))
        return jax.jit(epoch_fn, donate_argnums=(0, 1, 2))

    def _build_pred_step(self):
        def step(params, *xs):
            tr, state = _split_state(params)  # keep running stats f32
            preds = self._forward(_merge_state(self._cast_compute(tr),
                                               state),
                                  self._cast_compute(list(xs)),
                                  training=False, rng=None, collect=None)
            return jax.tree.map(
                lambda p: p.astype(jnp.float32)
                if hasattr(p, "dtype") and p.dtype == jnp.bfloat16 else p,
                preds)
        return jax.jit(step)

    def lower_train_hlo(self, x, y=None, batch_size: int = 32,
                        feature_cols=None, label_cols=None,
                        seed: int = 0) -> str:
        """Optimized-HLO text of the jitted single-batch train step at
        these shapes and the current mesh's shardings — the input to
        sharding-quality checks (``zoo_tpu.parallel.hlo_check``): a
        silently-replicating sharding regression still trains with finite
        loss, but its compiled collective mix (no all-gather under FSDP,
        a full-param all-gather under pure DP, ...) gives it away.
        Note: ``.lower().compile()`` is AOT — it does NOT share or
        populate fit's jit call cache, so this costs one extra compile
        at these shapes."""
        if self.loss_fn is None:
            raise RuntimeError("call compile() before lower_train_hlo()")
        xs, ys = data_utils.to_xy_arrays(x, y, feature_cols, label_cols)
        xs = self._adapt_inputs(xs)
        ys_list = list(ys) if isinstance(ys, (list, tuple)) else [ys]
        self.build(jax.random.PRNGKey(seed),
                   [(None,) + a.shape[1:] for a in xs])
        params = self._place(self.params)
        tx = self.optimizer.make()
        trainable, _ = _split_state(params)
        opt_state = self._opt_state or (
            self.optimizer.init_fused(trainable)
            if getattr(self.optimizer, "fused", False) else
            tx.init(trainable))
        if self._active_guard() is not None:
            # the guarded step carries the guard counters in opt_state
            opt_state = (opt_state, self._active_guard().device_init())
        rng = jax.random.PRNGKey(seed + 1)
        mesh = self._mesh()
        _shard = None
        if mesh is not None and mesh.size > 1:
            from zoo_tpu.parallel.plans import ensure_placed
            opt_state = ensure_placed(opt_state, mesh)
            _shard = self._state_shardings(params, opt_state)
            rng = jax.device_put(rng, _shard[2])
        local_bs = max(batch_size // jax.process_count(), 1)
        host_batch = [np.asarray(a[:local_bs]) for a in xs + ys_list]
        batch = self._put_batch(host_batch)
        # use OUR jitted step, never an interposed _jit_train (the
        # elastic-retry fault-injection contract replaces it with plain
        # callables that have no .lower); don't clobber the interposer
        jt = getattr(self, "_own_jit_train", None)
        interposed = self._jit_train is not None \
            and self._jit_train is not jt
        if not interposed and getattr(self, "_jit_mesh", None) != mesh:
            self._drop_train_caches()  # stale-mesh shardings baked in
            jt = None
            self._jit_mesh = mesh
        if jt is None:
            jt = self._own_jit_train = self._build_train_step(
                self._step_shardings(_shard,
                                     [a.ndim for a in host_batch], False))
        if self._jit_train is None:
            self._jit_train = jt
        return jt.lower(params, opt_state, rng,
                        *batch).compile().as_text()

    # -- training loop ----------------------------------------------------
    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, shuffle: bool = True,
            feature_cols=None, label_cols=None, seed: int = 0,
            verbose: int = 1) -> Dict[str, List[float]]:
        """reference: ``KerasNet.fit`` ``Topology.scala:347`` (trains via
        InternalDistriOptimizer there; a jitted step loop here)."""
        if getattr(self, "_quantized", False):
            raise RuntimeError(
                "this model was int8-quantized (quantize_model) and is "
                "inference-only; re-load the float checkpoint to train")
        if self.loss_fn is None:
            raise RuntimeError("call compile() before fit()")
        xs, ys = data_utils.to_xy_arrays(x, y, feature_cols, label_cols)
        xs = self._adapt_inputs(xs)
        if ys is None:
            raise ValueError("fit requires labels")
        n_out = len(getattr(self, "outputs", [None]))
        if isinstance(ys, (list, tuple)):
            if n_out <= 1:
                # single-output model: a list of per-sample label rows is
                # ONE label array, not a multi-output label set
                ys = np.stack([np.asarray(a) for a in ys]) \
                    if len(ys) > 1 else np.asarray(ys[0])
                ys_list = [ys]
            elif len(ys) != n_out:
                raise ValueError(f"model has {n_out} outputs but got "
                                 f"{len(ys)} label arrays")
            else:
                ys_list = list(ys)
        else:
            ys_list = [ys]
        n = data_utils.num_samples(xs)

        mesh = self._mesh()
        if mesh is not None:
            from zoo_tpu.parallel.mesh import validate_batch_size
            validate_batch_size(batch_size, mesh)
        # multi-host SPMD: ``batch_size`` is the GLOBAL batch; each process
        # feeds its local rows (batch_size / process_count). Every process
        # must hold the same local sample count so step counts agree.
        pc = jax.process_count()
        if batch_size % pc:
            raise ValueError(f"batch_size ({batch_size}) must divide by "
                             f"process_count ({pc})")
        local_bs = batch_size // pc
        if n < local_bs:
            raise ValueError(f"local dataset ({n}) smaller than per-process "
                             f"batch ({local_bs})")

        self.build(jax.random.PRNGKey(seed),
                   [(None,) + a.shape[1:] for a in xs])
        params = self._place(self.params)
        tx = self.optimizer.make()
        trainable, _ = _split_state(params)
        opt_state = self._opt_state or (
            self.optimizer.init_fused(trainable)
            if getattr(self.optimizer, "fused", False) else
            tx.init(trainable))
        if (self._opt_state is not None and mesh is not None
                and mesh.size > 1 and self._plan_name() != "auto"):
            # reshard-on-restore for plan-sharded moments: a checkpoint
            # restore places leaves mesh-generically (replicated for a
            # pipe/expert-sharded shape), but a previously compiled step
            # expects the plan layout; pin every moment back onto the
            # shardings a fresh init of the placed params carries
            from zoo_tpu.parallel.plans import shardings_of
            tmpl = (self.optimizer.init_fused(trainable)
                    if getattr(self.optimizer, "fused", False)
                    else tx.init(trainable))
            opt_state = jax.tree_util.tree_map(
                lambda s, a: jax.device_put(a, s),
                shardings_of(tmpl, mesh), opt_state)

        guard = self._active_guard()
        if guard is not None:
            guard.begin_fit()
            # the guard's device-side (bad, streak) counters ride the
            # optimizer-state carry; the guarded step unwraps them
            opt_state = (opt_state, guard.device_init())
        # >1-device mesh: commit every state leaf to its plan sharding and
        # capture the explicit in/out shardings the jitted steps are built
        # with (params/opt-state per the plan, guard counters replicated)
        _shard = None
        _coll_est = None
        if mesh is not None and mesh.size > 1:
            from zoo_tpu.parallel.plans import (
                ensure_placed,
                estimate_collective_bytes,
            )
            opt_state = ensure_placed(opt_state, mesh)
            _shard = self._state_shardings(params, opt_state)
            _plan = self._plan_name()
            _act_bytes = 0
            if _plan in ("pipeline", "moe"):
                # activation proxy at the stage/expert cut: one local
                # batch of input rows (the static estimate only needs
                # the order of magnitude the ring/all_to_all moves)
                _act_bytes = local_bs * sum(
                    int(np.prod(a.shape[1:], dtype=np.int64))
                    * a.dtype.itemsize for a in xs)
            _coll_est = {k: v for k, v in estimate_collective_bytes(
                trainable, mesh, _plan, activation_bytes=_act_bytes,
                n_microbatch=self._pipe_microbatches(
                    mesh.shape.get("pipe", 1))).items() if v}
        # boundary bookkeeping: per-epoch cumulative baselines so each
        # superbatch boundary sees window deltas (reset at epoch start)
        gb = {"loss": 0.0, "steps": 0, "bad": 0, "bad0": 0, "idx": None,
              "n": 0}

        def _guard_boundary(epoch, final=False):
            """Superbatch-boundary guard check: read the device counters
            (the only host sync the guard adds), escalate to rollback /
            preempt when the controller says so."""
            nonlocal params, opt_state, loss_sum, n_steps
            gb["n"] += 1
            if not (final or guard.preempt_requested
                    or gb["n"] % guard.config.check_every == 0):
                return
            inner, gstate = opt_state
            g = jax.device_get(gstate)
            cur = float(np.asarray(loss_sum)) if loss_sum is not None \
                else 0.0
            act = guard.on_boundary(
                bad_total=int(g["bad"]), streak=int(g["streak"]),
                window_loss=cur - gb["loss"],
                window_steps=n_steps - gb["steps"],
                global_step=self._step, epoch=epoch,
                batch_hint=gb["idx"])
            gb["loss"], gb["steps"], gb["bad"] = cur, n_steps, int(g["bad"])
            if act == "rollback":
                state, aux, lr_scale = guard.rollback()
                params = self._place(state["params"])
                tr, _ = _split_state(params)
                inner = aux if aux is not None else (
                    self.optimizer.init_fused(tr)
                    if getattr(self.optimizer, "fused", False)
                    else tx.init(tr))
                if _shard is not None and aux is not None:
                    # reshard-on-restore: the checkpointed opt state is
                    # host numpy; pin every moment back onto the SAME
                    # mesh layout the step was compiled for, so rollback
                    # under FSDP/TP keeps PR 4 semantics bit-unchanged
                    inner = jax.tree_util.tree_map(
                        lambda s, a: jax.device_put(a, s),
                        _shard[1][0], inner)
                hp = getattr(inner, "hyperparams", None)
                if lr_scale != 1.0 and hp is not None \
                        and "learning_rate" in hp:
                    hp["learning_rate"] = jnp.asarray(
                        float(np.asarray(hp["learning_rate"])) * lr_scale,
                        jnp.float32)
                opt_state = (inner, guard.device_init())
                if _shard is not None:
                    from zoo_tpu.parallel.plans import ensure_placed
                    opt_state = ensure_placed(opt_state, mesh)
                gb["bad"] = gb["bad0"] = 0
                if not final:
                    # the diverged pre-rollback losses must not leak
                    # into this epoch's reported loss/throughput: the
                    # epoch restarts its accumulators at the restore
                    # point (a rollback AT epoch end keeps them — that
                    # epoch really did diverge, and its loss says so)
                    loss_sum, n_steps = None, 0
                    gb["loss"], gb["steps"] = 0.0, 0
            elif act == "preempt":
                # commit the CURRENT state to the model so the owner's
                # save callback snapshots exactly this step, then save
                # (coordinated across hosts) and exit resume-don't-retry
                self.params = jax.device_get(params) if mesh is None \
                    else params
                self._opt_state = inner
                guard.preempt_checkpoint(step=self._step)

        rng = jax.random.PRNGKey(seed + 1)
        if _shard is not None:
            rng = jax.device_put(rng, _shard[2])
        nprng = np.random.RandomState(seed)
        val_arrays = None
        if validation_data is not None:
            val_arrays = data_utils.to_xy_arrays(
                validation_data[0] if isinstance(validation_data, tuple)
                else validation_data,
                validation_data[1] if isinstance(validation_data, tuple)
                and len(validation_data) > 1 else None,
                feature_cols, label_cols)
            val_arrays = (self._adapt_inputs(val_arrays[0]), val_arrays[1])
        history: Dict[str, List[float]] = {"loss": []}
        from zoo_tpu.orca.data.ingest import staged_pipeline
        arrs = xs + ys_list
        sample_bytes = sum(a[:1].nbytes for a in arrs)
        # Host→HBM transfers are chunked into SUPERBATCHES (many training
        # batches per device_put, ~64MB or 16 batches) and sliced on-device:
        # per-batch puts pay a full transport round trip each (~100ms on a
        # tunneled PJRT backend) which no depth-2 prefetch can hide. The
        # staging thread still overlaps transfer with compute.
        device_resident = all(hasattr(a, "devices") for a in arrs)
        if device_resident:
            # dataset already lives in HBM: slicing is device-side, so the
            # 64MB host-transfer budget does not apply; a deep scan group
            # amortizes per-dispatch overhead (13-90ms on tunneled PJRT)
            group = 64
        else:
            group = max(1, min(16, (64 << 20) // max(sample_bytes * local_bs,
                                                     1)))
        if pc > 1:
            # a staged multi-host global array cannot be host-sliced into
            # sub-batches; assemble exactly one global batch per put
            group = 1
        n_batches = max(n // local_bs, 1)
        prof = getattr(self, "_profiler", None)
        # k steps per dispatch via lax.scan. Not taken when: the profiler
        # needs per-step dispatch boundaries; multi-host (per-process
        # global assembly is one batch at a time); a caller interposed on
        # _jit_train (the elastic-retry fault-injection contract routes
        # every step through it); or the batch count has no divisor in
        # [2, group] (a ragged scan tail would force a second compile —
        # the plain path then keeps the transfer-chunked group as-is).
        scan_group = min(group, n_batches)
        while scan_group > 1 and n_batches % scan_group:
            scan_group -= 1
        # "interposed" = somebody replaced _jit_train with their own
        # wrapper (the elastic-retry fault-injection contract); our own
        # cached build (e.g. from a profiled fit) must not disable scan
        interposed = self._jit_train is not None \
            and self._jit_train is not getattr(self, "_own_jit_train", None)
        if not interposed and getattr(self, "_jit_mesh", None) != mesh:
            # cached steps bake their explicit shardings in; a context
            # switch to a different mesh (AutoML sub-meshes, re-init)
            # must rebuild them, never feed a stale-mesh executable
            self._drop_train_caches()
            self._jit_mesh = mesh
        # whole-epoch dispatch: small device-resident dataset on one chip
        # -> permutation-gather + full-epoch scan in ONE jit call per
        # epoch (see _build_epoch_train_step). The 256MB cap bounds the
        # permuted-copy HBM cost; the even-division requirement avoids a
        # ragged tail batch forcing a second compile.
        use_epoch = (device_resident and pc == 1
                     and prof is None and not interposed
                     and n % local_bs == 0 and n_batches >= 2
                     and sum(a.nbytes for a in arrs) <= (256 << 20))
        use_scan = scan_group > 1 and prof is None and pc == 1 \
            and not interposed and not use_epoch
        batch_ndims = [a.ndim for a in arrs]
        if use_epoch:
            if getattr(self, "_jit_epoch_cache", None) is None:
                self._jit_epoch_cache = {}
        elif use_scan:
            group = scan_group
            # getattr: instances unpickled from blobs predating _jit_multi
            if getattr(self, "_jit_multi", None) is None:
                self._jit_multi = self._build_multi_train_step(
                    self._step_shardings(_shard, batch_ndims, True))
        elif self._jit_train is None:
            self._jit_train = self._own_jit_train = \
                self._build_train_step(
                    self._step_shardings(_shard, batch_ndims, False))
        # host-fed path: stage superbatch slices into rotating
        # preallocated buffers (double-buffered device_put — the DMA of
        # superbatch k reads buffer A while k+1 is sliced into buffer
        # B). maybe_create allocates the buffers off XLA's zero-copy
        # alignment and probes each one, falling back to plain
        # allocation if device_put would alias it; multi-host keeps the
        # global-assembly path, and a multi-device CPU mesh is excluded
        # (its per-device placement semantics are not covered by the
        # probe).
        staging_pool = None
        if not device_resident and pc == 1 and (
                mesh is None or getattr(mesh, "size", 1) == 1
                or jax.default_backend() != "cpu"):
            from zoo_tpu.orca.data.ingest import StagingBufferPool
            staging_pool = StagingBufferPool.maybe_create(
                arrs, rows=group * local_bs)
        for epoch in range(nb_epoch):
            t0 = time.perf_counter()  # monotonic: NTP-step-proof Throughput
            loss_sum, n_steps = None, 0
            gb["loss"], gb["steps"] = 0.0, 0  # per-epoch loss baselines
            gb["bad0"] = gb["bad"]
            if use_epoch:
                kk = n // local_bs
                # mesh identity in the key: the built closure bakes the
                # mesh in (sharding constraint), so a context change must
                # not reuse a stale-mesh epoch fn. Mesh is value-hashable
                # (axis names + device array incl. shape), unlike id()
                # which a GC'd mesh can leak to a new object.
                key = (kk, local_bs, bool(shuffle), mesh)
                je = self._jit_epoch_cache.get(key)
                if je is None:
                    je = self._jit_epoch_cache[key] = \
                        self._build_epoch_train_step(kk, local_bs,
                                                     bool(shuffle),
                                                     shard=_shard)
                extra_args = []
                if shuffle:
                    perm = nprng.permutation(n).astype(np.int32)
                    extra_args = [jnp.asarray(perm)]
                params, opt_state, rng, loss_sum = je(
                    params, opt_state, rng, *arrs, *extra_args)
                self._step += kk
                n_steps = kk
            else:
                if device_resident and (mesh is None or mesh.size == 1):
                    # HBM-resident dataset on one chip: gather + reshape for a
                    # whole superbatch in ONE jitted call. Python-level
                    # per-array slicing costs 2 dispatches per array, and
                    # per-dispatch overhead on tunneled PJRT backends has been
                    # measured at 13-90ms — for small-sample models (NCF) that
                    # made the HBM-staged path slower than feeding from host.
                    if getattr(self, "_jit_stage", None) is None:
                        import functools

                        @functools.partial(jax.jit, static_argnums=(2, 3))
                        def _jit_stage(arrs, idx, k, bs):
                            out = [a[idx] for a in arrs]
                            if k:
                                out = [a.reshape((k, bs) + a.shape[1:])
                                       for a in out]
                            return out
                        self._jit_stage = _jit_stage

                    def _stage(idx):
                        k = len(idx) // local_bs if use_scan else 0
                        return self._jit_stage(arrs, jnp.asarray(idx), k,
                                               local_bs)
                    # device-side gather: one stage (the work IS the
                    # dispatch; splitting it buys nothing)
                    stages = [("stage", _stage)]
                else:
                    # host-fed path: separate slice and device-put
                    # stages, each on its own staging thread — the step
                    # on superbatch k overlaps the host→device transfer
                    # of k+1 AND the host slicing of k+2 (the async
                    # ingest pipeline; see orca/data/ingest.py).
                    # reset() reclaims buffers a prior epoch's teardown
                    # (error, guard rollback) stranded in flight; its
                    # generation token fences off that epoch's stage
                    # threads, which may still be running (the pipeline
                    # close() does not join) and must not touch THIS
                    # epoch's slots
                    pool_gen = (staging_pool.reset()
                                if staging_pool is not None else None)

                    def _slice(idx):
                        if staging_pool is not None:
                            sliced = staging_pool.take(arrs, idx,
                                                       gen=pool_gen)
                        else:
                            sliced = [a[idx] for a in arrs]
                        if guard is not None:
                            # chaos seam: armed tests corrupt the host
                            # batch in place (poison-batch injection);
                            # the idx hint feeds quarantine records
                            # (approximate — the slice stage runs one
                            # superbatch ahead of the step)
                            gb["idx"] = (int(idx[0]), int(idx[-1]))
                            from zoo_tpu.util.resilience import (
                                fault_point,
                            )
                            fault_point("fit.batch", arrays=sliced,
                                        idx=idx)
                        if use_scan:  # (k*bs,...) -> (k, bs, ...) for scan
                            sliced = [a.reshape((len(idx) // local_bs,
                                                 local_bs)
                                                + a.shape[1:])
                                      for a in sliced]
                        return sliced

                    def _put(sliced):
                        out = self._put_stacked(sliced) if use_scan \
                            else self._put_batch(sliced)
                        if staging_pool is not None:
                            # the buffer may be reused only after the
                            # host→device transfer has READ it; blocking
                            # here costs nothing — this IS the transfer
                            # stage's thread, and the step consumes
                            # `out` downstream anyway
                            jax.block_until_ready(out)
                            staging_pool.recycle(gen=pool_gen)
                        return out

                    stages = [("slice", _slice), ("device_put", _put)]

                # stage fns run on the pipeline's daemon threads; pin
                # the CALLER's runtime context (possibly a thread-local
                # sub-mesh scope, e.g. concurrent AutoML trials) so the
                # staged batches land on the same mesh as the params
                _caller_ctx = get_runtime_context(required=False)
                if _caller_ctx is not None:
                    from zoo_tpu.common.context import (
                        runtime_context_scope,
                    )

                    def _pin(fn, _ctx=_caller_ctx):
                        def pinned(item, _fn=fn):
                            with runtime_context_scope(_ctx):
                                return _fn(item)
                        return pinned

                    stages = [(name, _pin(fn)) for name, fn in stages]

                # depth=1: superbatches are large by design, and two
                # depth-2 stages would keep ~3 extra host copies
                # resident; one buffer per stage is all the overlap
                # needs (slice k+2 | transfer k+1 | step k)
                batches = staged_pipeline(
                    data_utils.batch_slices(n, local_bs, shuffle, nprng,
                                            group=group),
                    stages, depth=1)
                try:
                    with (prof.epoch_trace() if prof
                          else contextlib.nullcontext()):
                        source = (prof.timed_iter(iter(batches), "data")
                                  if prof else batches)
                        for staged in source:
                            if use_scan:
                                k = staged[0].shape[0]
                                params, opt_state, rng, loss = self._jit_multi(
                                    params, opt_state, rng, *staged)
                                self._step += k
                                n_steps += k
                                loss_sum = loss if loss_sum is None \
                                    else loss_sum + loss
                                if guard is not None:
                                    _guard_boundary(epoch)
                                continue
                            n_sub = (staged[0].shape[0] // local_bs
                                     if group > 1 else 1)
                            for j in range(n_sub):
                                if group > 1:
                                    # re-place the sub-slice so a multi-device
                                    # mesh keeps the guaranteed batch sharding
                                    # (device-to-device; a no-op on one chip)
                                    with (prof.phase("reshard") if prof
                                          else contextlib.nullcontext()):
                                        sub = self._put_batch(
                                            [t[j * local_bs:(j + 1) * local_bs]
                                             for t in staged])
                                else:
                                    sub = staged
                                if prof:
                                    with prof.phase("step"):
                                        params, opt_state, rng, loss = \
                                            self._jit_train(params, opt_state,
                                                            rng, *sub)
                                        if prof.sync:
                                            # sync so the phase measures the
                                            # real device step, not dispatch
                                            jax.block_until_ready(loss)
                                else:
                                    params, opt_state, rng, loss = \
                                        self._jit_train(params, opt_state,
                                                        rng, *sub)
                                self._step += 1
                                n_steps += 1
                                # running device-side sum: one host transfer
                                # per epoch (a per-step sync pays a full round
                                # trip — ~100ms over a tunneled PJRT transport)
                                loss_sum = loss if loss_sum is None \
                                    else loss_sum + loss
                            if guard is not None:
                                _guard_boundary(epoch)
                finally:
                    batches.close()
            if guard is not None:
                _guard_boundary(epoch, final=True)
                # skipped steps contributed 0 to the sanitized loss sum;
                # keep them out of the mean too
                denom = max(n_steps - max(0, gb["bad"] - gb["bad0"]), 1)
            else:
                denom = max(n_steps, 1)
            if guard is not None and loss_sum is None:
                # a mid-epoch rollback wiped every step of this epoch:
                # the epoch effectively did not run and there is no
                # honest loss to report. Raise the typed error the
                # Estimator's retry perimeter turns into "restore the
                # verified checkpoint and retrain the lost epoch" —
                # the guard ladder's designed endWhen semantics
                from zoo_tpu.orca.learn.guard import EpochRolledBack
                raise EpochRolledBack(
                    f"{self.name}: guard rollback wiped every step of "
                    f"epoch {epoch + 1}; retrain it from the restored "
                    "checkpoint")
            epoch_loss = float(np.asarray(loss_sum)) / denom
            from zoo_tpu.common.context import ZooContext
            if ZooContext.debug_nans and not np.isfinite(epoch_loss):
                raise FloatingPointError(
                    f"{self.name}: non-finite training loss "
                    f"({epoch_loss}) in epoch {epoch + 1} — NaN-check "
                    "mode (ZooContext.debug_nans) treats this as fatal; "
                    "jax_debug_nans should have pinpointed the producing "
                    "op above")
            if _coll_est:
                # static plan estimate x steps actually executed: the
                # obs-side answer to "what did this epoch move over ICI"
                for op_, nbytes_ in _coll_est.items():
                    _collective_bytes.labels(op=op_).inc(
                        float(nbytes_) * n_steps)
            history["loss"].append(epoch_loss)
            self.train_summary.add_scalar("Loss", epoch_loss, self._step)
            self.train_summary.add_scalar(
                "Throughput",
                n_steps * batch_size / max(time.perf_counter() - t0, 1e-9),
                self._step)
            if val_arrays is not None:
                vx, vy = val_arrays
                self.params = params  # evaluate on current params
                with (prof.phase("eval") if prof
                      else contextlib.nullcontext()):
                    val = self._evaluate_arrays(vx, vy, batch_size)
                for k, v in val.items():
                    history.setdefault("val_" + k, []).append(v)
                    self.validation_summary.add_scalar(k, v, self._step)
            if prof:
                for tag, val_ms in prof.epoch_scalars().items():
                    self.train_summary.add_scalar(tag, val_ms, self._step)
            plateau = getattr(self.optimizer, "plateau", None)
            if plateau is not None:
                mon = plateau.monitor
                if mon.lower() == "loss":
                    watched = epoch_loss
                else:
                    series = history.get(mon) or history.get("val_" + mon)
                    watched = series[-1] if series else None
                if watched is None:
                    import warnings
                    warnings.warn(
                        f"Plateau monitors '{mon}' but no such series was "
                        "produced this epoch (pass validation_data / the "
                        "metric); skipping lr adjustment")
                else:
                    new_lr = plateau.update(watched)
                    # inject_hyperparams keeps lr in the optimizer state, so
                    # the jitted step picks the new value up as an argument
                    _inner_opt = opt_state[0] if guard is not None \
                        else opt_state
                    new_lr = jnp.asarray(new_lr, dtype=jnp.float32)
                    if _shard is not None:
                        # keep the explicit in_shardings contract: every
                        # opt-state leaf stays mesh-placed (replicated)
                        new_lr = jax.device_put(new_lr, _shard[2])
                    _inner_opt.hyperparams["learning_rate"] = new_lr
            if verbose:
                extra = {k: v[-1] for k, v in history.items() if k != "loss"}
                print(f"Epoch {epoch + 1}/{nb_epoch} - loss: "
                      f"{epoch_loss:.4f}" +
                      "".join(f" - {k}: {v:.4f}" for k, v in extra.items()))
        self.params = jax.device_get(params) if mesh is None else params
        if guard is not None:
            opt_state = opt_state[0]  # shed the guard counters
        self._opt_state = opt_state
        return history

    # -- evaluation / inference -------------------------------------------
    def _shard_multiple(self) -> int:
        mesh = self._mesh()
        if mesh is None:
            return 1
        from zoo_tpu.parallel.mesh import data_axes
        denom = 1
        for a in data_axes(mesh):
            denom *= mesh.shape[a]
        return denom

    def _predict_arrays(self, xs, batch_size: int) -> np.ndarray:
        """Predictions for this process's rows. On a multi-host mesh each
        process feeds its local rows of the global batch and gets its local
        predictions back (``batch_size`` is global, like fit)."""
        if self._jit_pred is None:
            with _JIT_BUILD_LOCK:
                if self._jit_pred is None:
                    self._jit_pred = self._build_pred_step()
        params = self._place(self.params)
        n = data_utils.num_samples(xs)
        pc = jax.process_count()
        mult = max(1, self._shard_multiple() // pc)
        local_target = max(1, batch_size // pc)
        bs = max(mult, (min(local_target, n) // mult) * mult)
        mesh = self._mesh()
        outs = []
        for idx in data_utils.batch_slices(n, bs, False,
                                           drop_remainder=False):
            chunk = [a[idx] for a in xs]
            padded, real = data_utils.pad_batch(chunk, bs)
            preds = self._jit_pred(params, *self._put_batch(padded))
            if pc > 1:
                # bring back only this process's rows of the global output
                from jax.experimental import multihost_utils
                from zoo_tpu.parallel.mesh import batch_sharding

                def _localize(p):
                    out = multihost_utils.global_array_to_host_local_array(
                        p, mesh, batch_sharding(mesh, p.ndim).spec)
                    return jnp.asarray(out)

                preds = tuple(_localize(p) for p in preds) \
                    if isinstance(preds, tuple) else _localize(preds)
            # stays on device (lazy slice) — batches pipeline without a
            # per-batch host sync; ONE transfer at the end
            if isinstance(preds, tuple):
                outs.append(tuple(p[:real] if real != bs else p
                                  for p in preds))
            else:
                outs.append(preds[:real] if real != bs else preds)
        if outs and isinstance(outs[0], tuple):
            return tuple(np.asarray(jnp.concatenate([o[i] for o in outs],
                                                    axis=0))
                         for i in range(len(outs[0])))
        return np.asarray(jnp.concatenate(outs, axis=0))

    def _evaluate_arrays(self, xs, ys, batch_size) -> Dict[str, float]:
        """Exact STREAMING evaluation: per-batch loss/metric partials
        accumulate on device — O(1) host memory and one device→host sync
        regardless of dataset size (the reference streams its
        ValidationMethod aggregation per batch the same way; round-1
        materialized the full prediction array on host)."""
        if len(getattr(self, "outputs", [None])) > 1:
            # multi-output: combined loss over the heads; per-head metrics
            # are not aggregated (pass per-head eval sets instead)
            preds = self._predict_arrays(xs, batch_size)
            yt = [jnp.asarray(a) for a in ys] \
                if isinstance(ys, (list, tuple)) else jnp.asarray(ys)
            if self.loss_fn is None:
                return {}
            return {"loss": float(self.loss_fn(
                yt, tuple(jnp.asarray(p) for p in preds)))}
        if self._jit_pred is None:
            with _JIT_BUILD_LOCK:
                if self._jit_pred is None:
                    self._jit_pred = self._build_pred_step()
        params = self._place(self.params)
        ys = np.asarray(ys) if not hasattr(ys, "devices") else ys
        n = data_utils.num_samples(xs)
        mult = self._shard_multiple()
        bs = max(mult, (min(batch_size, n) // mult) * mult)
        loss_sum = None
        totals = {m.name: None for m in self.metrics}
        seen = 0
        for idx in data_utils.batch_slices(n, bs, False,
                                           drop_remainder=False):
            chunk = [a[idx] for a in xs]
            yb = ys[idx]
            padded, real = data_utils.pad_batch(chunk, bs)
            preds = self._jit_pred(params, *self._put_batch(padded))
            preds = preds[:real]  # lazy device slice, no sync
            yt = jnp.asarray(yb)
            if self.loss_fn is not None:
                contrib = self.loss_fn(yt, preds) * real
                loss_sum = contrib if loss_sum is None \
                    else loss_sum + contrib
            for m in self.metrics:
                s, c = m.batch_eval(yt, preds)
                prev = totals[m.name]
                totals[m.name] = (s, c) if prev is None \
                    else (prev[0] + s, prev[1] + c)
            seen += real
        out = {}
        if loss_sum is not None:
            out["loss"] = float(np.asarray(loss_sum)) / max(seen, 1)
        for m in self.metrics:
            s, c = totals[m.name]
            out[m.name] = float(np.asarray(m.finalize(s, c)))
        return out

    def evaluate(self, x, y=None, batch_size: int = 32,
                 feature_cols=None, label_cols=None) -> Dict[str, float]:
        """reference: ``KerasNet.evaluate`` ``Topology.scala:504``."""
        xs, ys = data_utils.to_xy_arrays(x, y, feature_cols, label_cols)
        xs = self._adapt_inputs(xs)
        if ys is None:
            raise ValueError("evaluate requires labels")
        if self.params is None:
            self.build(input_shapes=[(None,) + a.shape[1:] for a in xs])
        return self._evaluate_arrays(xs, ys, batch_size)

    def predict(self, x, batch_size: int = 256, feature_cols=None
                ) -> np.ndarray:
        """reference: ``KerasNet.predict`` (distributed Predictor.scala).
        Ragged tails are padded then trimmed (the reference pads per-thread
        batches for inference, ``tf_dataset.py`` per-thread batch)."""
        xs, _ = data_utils.to_xy_arrays(x, None, feature_cols, None)
        xs = self._adapt_inputs(xs)
        if self.params is None:
            self.build(input_shapes=[(None,) + a.shape[1:] for a in xs])
        return self._predict_arrays(xs, batch_size)

    # -- persistence -------------------------------------------------------
    def to_bytes(self) -> bytes:
        """Serialize the WHOLE model (architecture + weights) with
        cloudpickle — the rebuild of the reference's Scala module
        serialization (``SerializerSpec``-covered save/load round trips).
        jit caches and summaries are dropped; params go to host numpy."""
        import cloudpickle

        jt, je, jp = self._jit_train, self._jit_eval, self._jit_pred
        jm = getattr(self, "_jit_multi", None)
        jo = getattr(self, "_own_jit_train", None)
        jc = getattr(self, "_jit_epoch_cache", None)
        jmesh = getattr(self, "_jit_mesh", None)
        ts, vs, opt = self.train_summary, self.validation_summary, \
            self._opt_state
        prof = getattr(self, "_profiler", None)
        grd = getattr(self, "_guard", None)
        params = self.params
        try:
            self._jit_train = self._jit_eval = self._jit_pred = None
            self._jit_multi = None
            self._own_jit_train = None
            self._jit_stage = None
            self._jit_epoch_cache = None
            self._jit_mesh = None  # Mesh holds live Device handles
            self._opt_state = None
            self._profiler = None
            self._guard = None  # holds locks/events; owners re-attach
            self.train_summary = TrainSummary()
            self.validation_summary = TrainSummary()
            if params is not None:
                self.params = jax.tree_util.tree_map(np.asarray, params)
            return cloudpickle.dumps(self)
        finally:
            self._jit_train, self._jit_eval, self._jit_pred = jt, je, jp
            self._jit_multi = jm
            self._own_jit_train = jo
            self._jit_epoch_cache = jc
            self._jit_mesh = jmesh
            self.train_summary, self.validation_summary = ts, vs
            self._opt_state = opt
            self._profiler = prof
            self._guard = grd
            self.params = params

    def save(self, path: str):
        with open(path, "wb") as f:
            f.write(self.to_bytes())
        return path

    @staticmethod
    def load(path: str) -> "KerasNet":
        import cloudpickle

        with open(path, "rb") as f:
            return cloudpickle.load(f)

    def save_weights(self, path: str):
        host = jax.tree_util.tree_map(np.asarray, self.params)
        with open(path, "wb") as f:
            pickle.dump({"params": host, "step": self._step}, f)

    def load_weights(self, path: str):
        """Restore a ``save_weights`` blob. Params are keyed by layer
        position+type (``_param_keys``), so a checkpoint only restores
        into a structurally identical model — a mismatch (layer inserted/
        removed/retyped, or a shape change) is a hard error here, never a
        silent mis-restore."""
        with open(path, "rb") as f:
            blob = pickle.load(f)
        loaded = blob["params"]
        if self.params is None:
            try:  # materialize the model's own structure to validate
                self.build()
            except ValueError:
                pass  # input shape unknowable here: accept unvalidated
        if self.params is not None:
            def _shapes(tree):
                return {k: np.shape(v) for k, v in
                        jax.tree_util.tree_leaves_with_path(tree)}
            have, got = _shapes(self.params), _shapes(loaded)
            if have != got:
                missing = sorted(set(map(str, have)) - set(map(str, got)))
                extra = sorted(set(map(str, got)) - set(map(str, have)))
                changed = sorted(str(k) for k in have
                                 if k in got and have[k] != got[k])
                raise ValueError(
                    "checkpoint does not match this model's structure "
                    "(params are keyed by layer position+type, so layers "
                    "must match one-for-one). "
                    f"missing={missing[:5]} unexpected={extra[:5]} "
                    f"shape-changed={changed[:5]}")
        self.params = loaded
        self._step = blob.get("step", 0)
        return self

    def summary(self):
        lines = [f'Model: "{self.name}"', "-" * 60]
        total = 0
        params = self.params or {}
        for layer in self.layers:
            p = params.get(self._key_of(layer), {})
            cnt = layer.param_count(p)
            total += cnt
            lines.append(f"{layer.name:<30}{type(layer).__name__:<20}{cnt}")
        lines.append("-" * 60)
        lines.append(f"Total params: {total}")
        print("\n".join(lines))
        return total


class Sequential(KerasNet):
    """Linear stack (reference: ``Sequential`` ``Topology.scala:1029``,
    Python ``keras/engine/topology.py:49``)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(name=name)
        self._layers: List[Layer] = []

    @property
    def layers(self) -> List[Layer]:
        return self._layers

    def add(self, layer: Layer) -> "Sequential":
        self._layers.append(layer)
        self.params = None  # invalidate
        return self

    def _input_shapes(self):
        if self._layers and self._layers[0].batch_input_shape is not None:
            return [self._layers[0].batch_input_shape]
        return None

    def _init_params(self, rng, input_shapes) -> Dict:
        shape = tuple(input_shapes[0])
        params: Dict = {}
        for layer in self._layers:
            rng, sub = jax.random.split(rng)
            params[self._key_of(layer)] = layer.build(sub, shape)
            shape = layer.compute_output_shape(shape)
        return params

    def _forward(self, params, inputs: List, *, training, rng, collect):
        h = inputs[0] if len(inputs) == 1 else inputs
        body = params.get(_PIPE_BODY_KEY) \
            if isinstance(params, dict) else None
        body_keys = set(getattr(self, "_pipe_body_keys", ()) or ())
        body_done = False
        for layer in self._layers:
            key = self._key_of(layer)
            if body is not None and key in body_keys:
                # the stacked homogeneous run applies as one unit (GPipe
                # schedule / scan) at the position of its first layer
                if not body_done:
                    h = self._apply_pipe_body(body, h, training=training)
                    body_done = True
                continue
            p = params.get(key, {})
            if collect is not None and hasattr(layer, "updated_stats") \
                    and training:
                collect[key] = {"stats": layer.updated_stats(p, h)}
            h = layer.call(p, h, training=training, rng=rng)
        return h

    # -- pipeline plan: body detection + stacking -------------------------
    def _find_pipe_body(self, params):
        """The longest contiguous run of layers with identical type,
        config, and param-tree signature — the candidate pipeline body.
        Returns ``(keys, template_layer)``; loud when no run exists."""
        def cfg_sig(layer):
            out = []
            for k, v in sorted(vars(layer).items()):
                if k.startswith("_") or k == "name":
                    continue
                if callable(v):
                    out.append((k, getattr(v, "__name__", str(type(v)))))
                elif isinstance(v, (int, float, str, bool, tuple)):
                    out.append((k, v))
                elif isinstance(v, list):
                    out.append((k, tuple(str(e) for e in v)))
            return tuple(out)

        best, cur, prev_sig = [], [], object()
        for layer in self._layers:
            p = params.get(self._key_of(layer), {})
            leaves = jax.tree_util.tree_flatten_with_path(p)[0]
            sig = None if not leaves else (
                type(layer).__name__, cfg_sig(layer),
                tuple((jax.tree_util.keystr(kp), tuple(np.shape(leaf)),
                       str(getattr(leaf, "dtype", "")))
                      for kp, leaf in leaves))
            if sig is not None and sig == prev_sig:
                cur.append(layer)
            else:
                cur = [layer] if sig is not None else []
            prev_sig = sig
            if len(cur) > len(best):
                best = list(cur)
        if len(best) < 2:
            raise ValueError(
                "plan='pipeline' needs a contiguous run of >= 2 "
                "identical layers (same type, config, and param "
                "shapes) to stage; this model has none")
        return [self._key_of(layer) for layer in best], best[0]

    def _stack_pipe_body(self, params):
        """Stack the body run's per-layer param dicts into one
        ``__pipe_body__`` entry with a leading layer dim — the tensor
        layout ``stack_stages`` splits and the pipeline plan shards
        over the ``pipe`` mesh axis."""
        if not isinstance(params, dict) or _PIPE_BODY_KEY in params:
            return params  # already stacked (compile-after-build)
        keys, template = self._find_pipe_body(params)
        body = [params[k] for k in keys]
        if any("stats" in p for p in body if isinstance(p, dict)):
            raise ValueError(
                "plan='pipeline' body layers must be stateless (the "
                "stacked stage scan cannot collect per-layer running "
                "stats); move BatchNorm-style layers out of the run")
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *body)
        out = {k: v for k, v in params.items() if k not in set(keys)}
        out[_PIPE_BODY_KEY] = stacked
        self._pipe_body_keys = tuple(keys)
        self._pipe_template = template
        return out

    def get_output_shape(self):
        shapes = self._input_shapes()
        shape = shapes[0]
        for layer in self._layers:
            shape = layer.compute_output_shape(shape)
        return shape


def Input(shape: Tuple, name: Optional[str] = None) -> KTensor:
    """Symbolic input (reference: ``Input`` in
    ``keras/engine/topology.py``; shape excludes batch)."""
    return KTensor((None,) + tuple(shape))


class Model(KerasNet):
    """Functional graph model (reference: ``Model`` ``Topology.scala:1145``
    Python ``keras/models.py``)."""

    def __init__(self, input: Union[KTensor, Sequence[KTensor]],
                 output: Union[KTensor, Sequence[KTensor]],
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.inputs = list(input) if isinstance(input, (list, tuple)) \
            else [input]
        self.outputs = list(output) if isinstance(output, (list, tuple)) \
            else [output]
        self.output = self.outputs[0]  # back-compat single-output attr
        self._topo = self._toposort()

    def _toposort(self) -> List[KTensor]:
        seen, order = set(), []

        def visit(node: KTensor):
            if id(node) in seen:
                return
            seen.add(id(node))
            for parent in node.inbound:
                visit(parent)
            order.append(node)

        for out in self.outputs:
            visit(out)
        for t in self.inputs:
            if id(t) not in seen:
                raise ValueError("an input tensor is not connected to output")
        return order

    @property
    def layers(self) -> List[Layer]:
        out, seen = [], set()
        for node in self._topo:
            if node.layer is not None and id(node.layer) not in seen:
                seen.add(id(node.layer))
                out.append(node.layer)
        return out

    def _input_shapes(self):
        return [t.shape for t in self.inputs]

    def _init_params(self, rng, input_shapes) -> Dict:
        params: Dict = {}
        shapes = {id(t): tuple(s) for t, s in zip(self.inputs, input_shapes)}
        for node in self._topo:
            if node.layer is None:
                continue
            in_shapes = [shapes[id(p)] for p in node.inbound]
            arg = in_shapes if len(in_shapes) > 1 else in_shapes[0]
            key = self._key_of(node.layer)
            if key not in params:  # shared layers build once
                rng, sub = jax.random.split(rng)
                params[key] = node.layer.build(sub, arg)
            shapes[id(node)] = node.layer.compute_output_shape(arg)
        return params

    def _forward(self, params, inputs: List, *, training, rng, collect):
        values = {id(t): v for t, v in zip(self.inputs, inputs)}
        for node in self._topo:
            if node.layer is None:
                if id(node) not in values:
                    raise ValueError("missing input value")
                continue
            args = [values[id(p)] for p in node.inbound]
            arg = args if len(args) > 1 else args[0]
            key = self._key_of(node.layer)
            p = params.get(key, {})
            if collect is not None and hasattr(node.layer, "updated_stats") \
                    and training:
                collect[key] = {
                    "stats": node.layer.updated_stats(p, arg)}
            values[id(node)] = node.layer.call(p, arg, training=training,
                                               rng=rng)
        if len(self.outputs) == 1:
            return values[id(self.output)]
        return tuple(values[id(o)] for o in self.outputs)
