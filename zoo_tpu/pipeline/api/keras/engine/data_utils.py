"""Data normalization helpers shared by the Keras facade and Orca estimators.

Rebuild of the input plumbing the reference spreads across
``pyzoo/zoo/orca/learn/utils.py`` (DataFrame/XShards → feature dicts) and
``tfpark/tf_dataset.py`` (ndarray feeds): everything becomes
``(list_of_input_arrays, label_array_or_None)`` host-side, then batches are
device_put with the batch sharding.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def to_xy_arrays(x, y=None, feature_cols: Optional[Sequence[str]] = None,
                 label_cols: Optional[Sequence[str]] = None
                 ) -> Tuple[List[np.ndarray], Optional[np.ndarray]]:
    """Normalize supported inputs to (inputs_list, labels).

    Accepts: numpy array(s), dict {"x": ..., "y": ...}, XShards of such
    dicts or of DataFrames (with feature_cols/label_cols), pandas DataFrame
    (with feature_cols/label_cols).
    """
    from zoo_tpu.orca.data.shard import LocalXShards

    if isinstance(x, LocalXShards):
        first = x.collect()[0]
        import pandas as pd
        if isinstance(first, pd.DataFrame):
            if not feature_cols:
                raise ValueError("feature_cols required for DataFrame shards")
            stacked = x.stack_numpy(list(feature_cols) + list(label_cols or []))
            xs = [stacked[c] for c in feature_cols]
            ys = _stack_labels([stacked[c] for c in (label_cols or [])])
            return xs, ys
        if isinstance(first, dict):
            stacked = x.stack_numpy()
            xs = _as_list(stacked.get("x"))
            ys = stacked.get("y")
            return xs, ys
        raise ValueError(f"unsupported shard type: {type(first)}")

    try:
        import pandas as pd
        if isinstance(x, pd.DataFrame):
            if not feature_cols:
                raise ValueError("feature_cols required for DataFrame input")
            missing = [c for c in list(feature_cols) + list(label_cols or [])
                       if c not in x.columns]
            if missing:
                raise ValueError(f"feature/label column(s) not found: "
                                 f"{missing}; available: {list(x.columns)}")
            xs = [x[c].to_numpy() for c in feature_cols]
            ys = _stack_labels([x[c].to_numpy() for c in (label_cols or [])])
            return xs, ys
    except ImportError:
        pass

    if isinstance(x, dict):
        return _as_list(x["x"]), _normalize_labels(x.get("y"))
    return _as_list(x), _normalize_labels(y)


def _normalize_labels(y):
    """A list is a multi-output label SET only when its elements are
    array-like; a plain python list of scalars (keras-style
    ``fit(x, [0, 1, ...])``) is one label array."""
    if y is None:
        return None
    if isinstance(y, (list, tuple)):
        if y and all((isinstance(a, np.ndarray) or hasattr(a, "devices"))
                     and np.ndim(a) >= 1 for a in y):
            return [_keep_device(a) for a in y]
        return np.asarray(y)  # python list of scalars / nested lists
    return _keep_device(y)


def _keep_device(a):
    """np-convert unless it's already a device (jax) array — a dataset
    cached in HBM must not be pulled back to host just to be re-sliced."""
    if a is None or hasattr(a, "devices"):
        return a
    return np.asarray(a)


def _as_list(x) -> List[np.ndarray]:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return [_keep_device(a) for a in x]
    return [_keep_device(x)]


def _stack_labels(cols: List[np.ndarray]) -> Optional[np.ndarray]:
    if not cols:
        return None
    if len(cols) == 1:
        return cols[0]
    return np.stack(cols, axis=-1)


def num_samples(xs: List[np.ndarray]) -> int:
    return int(xs[0].shape[0]) if xs else 0


def batch_slices(n: int, batch_size: int, shuffle: bool,
                 rng: Optional[np.random.RandomState] = None,
                 drop_remainder: bool = True, group: int = 1):
    """Yield index arrays, ``group`` whole batches at a time (group > 1 =
    superbatch staging: one host→device transfer covers several training
    batches). Training drops the ragged tail of the permutation (the
    reference enforces ``batch_size % cores == 0`` and fixed per-replica
    batches, ``tf_dataset.py:188``); inference pads instead (see
    ``pad_batch``)."""
    idx = np.arange(n)
    if shuffle:
        (rng or np.random).shuffle(idx)
    if drop_remainder:
        idx = idx[:(n // batch_size) * batch_size]
    chunk = batch_size * group
    for i in range(0, len(idx), chunk):
        yield idx[i:i + chunk]


def pad_batch(arrs: List[np.ndarray], batch_size: int
              ) -> Tuple[List[np.ndarray], int]:
    """Pad a ragged final batch up to ``batch_size`` by repeating row 0;
    returns (padded, real_count)."""
    real = arrs[0].shape[0]
    if real == batch_size:
        return arrs, real
    out = []
    for a in arrs:
        pad = np.repeat(a[:1], batch_size - real, axis=0)
        out.append(np.concatenate([a, pad], axis=0))
    return out, real
