"""Data normalization helpers shared by the Keras facade and Orca estimators.

Rebuild of the input plumbing the reference spreads across
``pyzoo/zoo/orca/learn/utils.py`` (DataFrame/XShards → feature dicts) and
``tfpark/tf_dataset.py`` (ndarray feeds): everything becomes
``(list_of_input_arrays, label_array_or_None)`` host-side, then batches are
device_put with the batch sharding.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


# full-materialization cap: a .repeat()ed / infinite dataset must fail
# with a message naming the cause, not OOM silently
_MAX_FOREIGN_BATCHES = 100_000


def _np_leaf(o):
    if "torch" in type(o).__module__:
        import torch
        if o.dtype == torch.bfloat16:  # .numpy() rejects bf16
            return o.detach().cpu().float().numpy()
        return o.detach().cpu().numpy()
    return o.numpy() if hasattr(o, "numpy") else np.asarray(o)


def _np_tree(o):
    if isinstance(o, (list, tuple)):
        return [_np_tree(v) for v in o]
    if isinstance(o, dict):
        return {k: _np_tree(v) for k, v in o.items()}
    return _np_leaf(o)


def _foreign_batches(x):
    """Return a numpy batch iterable when ``x`` is a torch DataLoader or
    a (batched) tf.data.Dataset; None otherwise. Datasets themselves
    (map-style torch Dataset, unbatched tf Dataset) are deliberately NOT
    accepted — they yield per-sample elements, not batches."""
    try:
        from torch.utils.data import DataLoader
        if isinstance(x, DataLoader):  # incl. user subclasses
            return (_np_tree(batch) for batch in x)
    except ImportError:
        pass
    if type(x).__module__.startswith("tensorflow") and \
            hasattr(x, "as_numpy_iterator") and hasattr(x, "element_spec"):
        spec = x.element_spec
        first = (spec[0] if isinstance(spec, (list, tuple)) else
                 next(iter(spec.values())) if isinstance(spec, dict)
                 else spec)
        if first.shape.rank is not None and (
                first.shape.rank == 0 or first.shape[0] is not None):
            raise ValueError(
                "tf.data.Dataset inputs must be batched (call "
                ".batch(n)); got elements of static shape "
                f"{first.shape} — if this IS a batched dataset, it was "
                "batched with drop_remainder=True; use "
                "drop_remainder=False or pass numpy arrays")
        return (_np_tree(b) for b in x.as_numpy_iterator())
    return None


def to_xy_arrays(x, y=None, feature_cols: Optional[Sequence[str]] = None,
                 label_cols: Optional[Sequence[str]] = None
                 ) -> Tuple[List[np.ndarray], Optional[np.ndarray]]:
    """Normalize supported inputs to (inputs_list, labels).

    Accepts: numpy array(s), dict {"x": ..., "y": ...}, XShards of such
    dicts or of DataFrames (with feature_cols/label_cols), pandas DataFrame
    (with feature_cols/label_cols), a torch ``DataLoader``, or a
    ``tf.data.Dataset`` of (x, y) batches (both materialized host-side —
    the reference's orca data bridges ``orca/data/tf/data.py`` /
    DataLoader feed did the same per-worker materialization).
    """
    from zoo_tpu.orca.data.shard import LocalXShards

    from zoo_tpu.orca.data.spark import is_spark_dataframe
    if is_spark_dataframe(x):
        # Spark DataFrame: executors write shard files, this process
        # loads its slice (no driver collect — orca/data/spark.py)
        from zoo_tpu.orca.data.spark import spark_dataframe_to_shards
        if y is not None:
            raise ValueError("labels come from label_cols for Spark "
                             "DataFrame input, not a separate y= "
                             "argument")
        if not feature_cols:
            raise ValueError("feature_cols required for Spark DataFrame "
                             "input")
        shards = spark_dataframe_to_shards(x, feature_cols, label_cols)
        return to_xy_arrays(shards, None, None, None)

    from zoo_tpu.orca.data.tf.data import Dataset as _OrcaTFDataset
    if isinstance(x, _OrcaTFDataset):
        if y is not None:
            raise ValueError("labels ride inside the Dataset elements, "
                             "not a separate y= argument")
        xs, ys = x.to_numpy()
        if isinstance(xs, dict):
            raise ValueError(
                "dict-of-columns Dataset cannot feed fit directly; "
                "map() it into (features, label) tuples first")
        return (_as_list(xs) if not isinstance(xs, list) else xs,
                _normalize_labels(ys))

    loader = _foreign_batches(x)
    if loader is not None:
        if y is not None:
            raise ValueError(
                "pass labels inside the DataLoader/Dataset batches, not "
                "as a separate y= argument")
        xs_b, ys_b = [], []
        for n, batch in enumerate(loader):
            if n >= _MAX_FOREIGN_BATCHES:
                raise ValueError(
                    f"dataset yielded more than {_MAX_FOREIGN_BATCHES} "
                    "batches — is it infinite (tf .repeat() / torch "
                    "IterableDataset)? Materialization needs a finite "
                    "dataset")
            if isinstance(batch, dict):  # {'x': ..., 'y': ...} collate
                bx, by = batch.get("x"), batch.get("y")
                if bx is None:
                    raise ValueError(
                        "dict batches must carry 'x' (and optionally "
                        f"'y'); got keys {sorted(batch)}")
            elif isinstance(batch, (list, tuple)):
                if len(batch) == 1:
                    bx, by = batch[0], None
                else:  # (x, y) or (x1, ..., xn, y): last item is labels
                    bx, by = list(batch[:-1]), batch[-1]
                    if len(bx) == 1:
                        bx = bx[0]
            else:
                bx, by = batch, None
            xs_b.append([np.asarray(a) for a in _as_list(bx)])
            if by is not None:
                ys_b.append(np.asarray(by))
        if not xs_b:
            raise ValueError("empty dataset/dataloader")
        xs = [np.concatenate([b[i] for b in xs_b])
              for i in range(len(xs_b[0]))]
        ys = np.concatenate(ys_b) if ys_b else None
        return xs, _normalize_labels(ys)

    if isinstance(x, LocalXShards):
        first = x.collect()[0]
        import pandas as pd
        if isinstance(first, pd.DataFrame):
            if not feature_cols:
                raise ValueError("feature_cols required for DataFrame shards")
            stacked = x.stack_numpy(list(feature_cols) + list(label_cols or []))
            xs = [stacked[c] for c in feature_cols]
            ys = _stack_labels([stacked[c] for c in (label_cols or [])])
            return xs, ys
        if isinstance(first, dict):
            stacked = x.stack_numpy()
            xs = _as_list(stacked.get("x"))
            ys = stacked.get("y")
            return xs, ys
        raise ValueError(f"unsupported shard type: {type(first)}")

    try:
        import pandas as pd
        if isinstance(x, pd.DataFrame):
            if not feature_cols:
                raise ValueError("feature_cols required for DataFrame input")
            missing = [c for c in list(feature_cols) + list(label_cols or [])
                       if c not in x.columns]
            if missing:
                raise ValueError(f"feature/label column(s) not found: "
                                 f"{missing}; available: {list(x.columns)}")
            xs = [x[c].to_numpy() for c in feature_cols]
            ys = _stack_labels([x[c].to_numpy() for c in (label_cols or [])])
            return xs, ys
    except ImportError:
        pass

    if isinstance(x, dict):
        return _as_list(x["x"]), _normalize_labels(x.get("y"))
    return _as_list(x), _normalize_labels(y)


def _normalize_labels(y):
    """A list is a multi-output label SET only when its elements are
    array-like; a plain python list of scalars (keras-style
    ``fit(x, [0, 1, ...])``) is one label array."""
    if y is None:
        return None
    if isinstance(y, (list, tuple)):
        if y and all((isinstance(a, np.ndarray) or hasattr(a, "devices"))
                     and np.ndim(a) >= 1 for a in y):
            return [_keep_device(a) for a in y]
        return np.asarray(y)  # python list of scalars / nested lists
    return _keep_device(y)


def _keep_device(a):
    """np-convert unless it's already a device (jax) array — a dataset
    cached in HBM must not be pulled back to host just to be re-sliced."""
    if a is None or hasattr(a, "devices"):
        return a
    return np.asarray(a)


def _as_list(x) -> List[np.ndarray]:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return [_keep_device(a) for a in x]
    return [_keep_device(x)]


def _stack_labels(cols: List[np.ndarray]) -> Optional[np.ndarray]:
    if not cols:
        return None
    if len(cols) == 1:
        return cols[0]
    return np.stack(cols, axis=-1)


def num_samples(xs: List[np.ndarray]) -> int:
    return int(xs[0].shape[0]) if xs else 0


def batch_slices(n: int, batch_size: int, shuffle: bool,
                 rng: Optional[np.random.RandomState] = None,
                 drop_remainder: bool = True, group: int = 1):
    """Yield index arrays, ``group`` whole batches at a time (group > 1 =
    superbatch staging: one host→device transfer covers several training
    batches). Training drops the ragged tail of the permutation (the
    reference enforces ``batch_size % cores == 0`` and fixed per-replica
    batches, ``tf_dataset.py:188``); inference pads instead (see
    ``pad_batch``)."""
    idx = np.arange(n)
    if shuffle:
        (rng or np.random).shuffle(idx)
    if drop_remainder:
        idx = idx[:(n // batch_size) * batch_size]
    chunk = batch_size * group
    for i in range(0, len(idx), chunk):
        yield idx[i:i + chunk]


def pad_batch(arrs: List[np.ndarray], batch_size: int
              ) -> Tuple[List[np.ndarray], int]:
    """Pad a ragged final batch up to ``batch_size`` by repeating row 0;
    returns (padded, real_count)."""
    real = arrs[0].shape[0]
    if real == batch_size:
        return arrs, real
    out = []
    for a in arrs:
        pad = np.repeat(a[:1], batch_size - real, axis=0)
        out.append(np.concatenate([a, pad], axis=0))
    return out, real
