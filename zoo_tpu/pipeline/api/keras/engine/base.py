"""Layer protocol for the Keras-style API.

Rebuild of the reference's BigDL-backed Keras-1 layer system
(``pyzoo/zoo/pipeline/api/keras/engine/topology.py`` + the Scala
``pipeline/api/keras/layers/**``). The reference builds a Scala module graph
behind Py4J handles; here a layer is a tiny Python object with

- ``build(rng, input_shape) -> params``  (a plain JAX pytree)
- ``call(params, inputs, *, training, rng) -> outputs``  (pure, jittable)
- ``compute_output_shape(input_shape)``

so a whole model is just (pytree of params, pure function) — exactly what
``jax.jit`` / ``jax.grad`` / ``pjit`` want. Shapes follow keras-1
conventions: ``input_shape`` excludes the batch dimension; ``None`` marks
the batch axis in reported shapes.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

_NAME_COUNTERS: Dict[str, int] = collections.defaultdict(int)


def _auto_name(cls_name: str) -> str:
    _NAME_COUNTERS[cls_name] += 1
    return f"{cls_name.lower()}_{_NAME_COUNTERS[cls_name]}"


# ---------------------------------------------------------------------------
# Initializers (keras-1 names; reference: KerasUtils.getInitMethod)
# ---------------------------------------------------------------------------

def get_initializer(name: Union[str, Callable]) -> Callable:
    if callable(name):
        return name
    name = (name or "glorot_uniform").lower()
    init = jax.nn.initializers
    table = {
        "glorot_uniform": init.glorot_uniform(),
        "glorot_normal": init.glorot_normal(),
        "he_uniform": init.he_uniform(),
        "he_normal": init.he_normal(),
        "lecun_uniform": init.lecun_uniform(),
        "lecun_normal": init.lecun_normal(),
        "uniform": init.uniform(scale=0.05),
        "normal": init.normal(stddev=0.05),
        "zero": init.zeros,
        "zeros": init.zeros,
        "one": init.ones,
        "ones": init.ones,
        "orthogonal": init.orthogonal(),
    }
    if name not in table:
        raise ValueError(f"unknown initializer: {name}")
    return table[name]


def get_activation_fn(name: Optional[Union[str, Callable]]) -> Optional[Callable]:
    if name is None or callable(name):
        return name
    name = name.lower()
    table = {
        "relu": jax.nn.relu,
        "relu6": jax.nn.relu6,
        "tanh": jnp.tanh,
        "sigmoid": jax.nn.sigmoid,
        "hard_sigmoid": jax.nn.hard_sigmoid,
        "softmax": jax.nn.softmax,
        "log_softmax": jax.nn.log_softmax,
        "softplus": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
        "elu": jax.nn.elu,
        "selu": jax.nn.selu,
        "gelu": jax.nn.gelu,
        "silu": jax.nn.silu,
        "swish": jax.nn.silu,
        "exp": jnp.exp,
        "linear": lambda x: x,
    }
    if name not in table:
        raise ValueError(f"unknown activation: {name}")
    return table[name]


# ---------------------------------------------------------------------------
# Symbolic tensors for the functional API
# ---------------------------------------------------------------------------

class KTensor:
    """Symbolic tensor node in the functional graph (the reference's
    ``Variable``/node handles built via Py4J)."""

    def __init__(self, shape: Tuple, layer: Optional["Layer"] = None,
                 inbound: Sequence["KTensor"] = (), dtype=jnp.float32):
        self.shape = tuple(shape)  # includes None batch dim
        self.layer = layer
        self.inbound = list(inbound)
        self.dtype = dtype

    def __repr__(self):
        lname = self.layer.name if self.layer else "input"
        return f"KTensor(shape={self.shape}, from={lname})"


class Layer:
    """Base layer. Subclasses implement ``build``/``call``/
    ``compute_output_shape`` (stateless pure functions of params)."""

    def __init__(self, input_shape: Optional[Tuple] = None,
                 name: Optional[str] = None, **kwargs):
        self.name = name or _auto_name(type(self).__name__)
        # keras-1: input_shape excludes the batch dim
        self.batch_input_shape = (None,) + tuple(input_shape) \
            if input_shape is not None else None
        self.built_shape = None

    # -- to override -----------------------------------------------------
    def build(self, rng, input_shape) -> Any:
        """Create params for ``input_shape`` (with leading None batch dim).
        Default: parameterless layer."""
        return {}

    def call(self, params, inputs, *, training: bool = False, rng=None):
        raise NotImplementedError

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)

    # -- functional API ---------------------------------------------------
    def __call__(self, x: Union[KTensor, Sequence[KTensor]]) -> KTensor:
        inbound = list(x) if isinstance(x, (list, tuple)) else [x]
        in_shape = ([t.shape for t in inbound] if len(inbound) > 1
                    else inbound[0].shape)
        out_shape = self.compute_output_shape(in_shape)
        return KTensor(out_shape, layer=self, inbound=inbound)

    # -- utilities --------------------------------------------------------
    def param_count(self, params) -> int:
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))

    def get_config(self) -> Dict:
        return {"name": self.name}

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name})"


def layer_rng(rng, layer_name: str):
    """Deterministic per-layer rng derivation for dropout etc. Uses a stable
    digest (NOT Python hash(), which is salted per process and would make
    SPMD hosts trace different fold_in constants)."""
    if rng is None:
        return None
    import zlib
    return jax.random.fold_in(rng, zlib.crc32(layer_name.encode()))


def normalize_shape(shape) -> Tuple:
    """Accept (None, ...) or (...) and return a (None, ...) shape."""
    shape = tuple(shape)
    if not shape or shape[0] is not None:
        return (None,) + shape
    return shape
