"""Autograd DSL: symbolic Variable math compiled into the layer graph.

Rebuild of ``pyzoo/zoo/pipeline/api/autograd.py:256-510`` (Variable wrapper
with operator overloads + the math function zoo: mean/abs/sum/clip/square/
sqrt/exp/log/pow/maximum/mm/batch_dot/l2_normalize/erf/...) and
``CustomLoss``. The reference compiles Variable expressions to BigDL graph
nodes via Py4J; here every op is a stateless graph layer whose ``call`` is
the jax expression itself, so a Variable expression IS a jittable function —
autograd comes from jax, not from a hand-built tape.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import KTensor, Layer
from zoo_tpu.pipeline.api.keras.engine.topology import Model


class _VarOp(Layer):
    """Stateless n-ary op node."""

    def __init__(self, fn: Callable, out_shape: Tuple, name=None):
        super().__init__(name=name)
        self.fn = fn
        self._out_shape = tuple(out_shape)

    def call(self, params, inputs, *, training=False, rng=None):
        if isinstance(inputs, list):
            return self.fn(*inputs)
        return self.fn(inputs)

    def compute_output_shape(self, input_shape):
        return self._out_shape


def _infer_shape(fn: Callable, shapes: Sequence[Tuple]) -> Tuple:
    # Trace twice with different batch sizes: if the leading output dim
    # tracks the batch it stays symbolic (None); otherwise (e.g. a
    # reduction over axis 0) the output shape is fully static.
    def trace(b):
        args = [jax.ShapeDtypeStruct((b,) + tuple(s[1:]), jnp.float32)
                for s in shapes]
        return jax.eval_shape(fn, *args)

    out2, out3 = trace(2), trace(3)
    if (len(out2.shape) == len(out3.shape) and out2.shape and
            out2.shape[0] == 2 and out3.shape[0] == 3):
        return (None,) + tuple(out2.shape[1:])
    return tuple(out2.shape)


class Variable:
    """Symbolic tensor with math operators (reference: ``Variable``,
    ``autograd.py:256``)."""

    def __init__(self, input_shape: Optional[Tuple] = None,
                 node: Optional[KTensor] = None, name: Optional[str] = None):
        if node is not None:
            self.node = node
        else:
            if input_shape is None:
                raise ValueError("pass input_shape or node")
            self.node = KTensor((None,) + tuple(input_shape))

    @property
    def shape(self):
        return self.node.shape

    # -- factory -----------------------------------------------------------
    @staticmethod
    def from_node(node: KTensor) -> "Variable":
        return Variable(node=node)

    # -- op plumbing -------------------------------------------------------
    @staticmethod
    def _apply(fn: Callable, *vars: "Variable",
               out_shape: Optional[Tuple] = None) -> "Variable":
        nodes = [v.node for v in vars]
        shape = out_shape or _infer_shape(fn, [n.shape for n in nodes])
        layer = _VarOp(fn, shape)
        return Variable(node=layer(nodes if len(nodes) > 1 else nodes[0]))

    def _binop(self, other, fn) -> "Variable":
        if isinstance(other, Variable):
            return Variable._apply(fn, self, other)
        return Variable._apply(lambda a: fn(a, other), self)

    # -- operators ---------------------------------------------------------
    def __add__(self, other):
        return self._binop(other, lambda a, b: a + b)

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, lambda a, b: a - b)

    def __rsub__(self, other):
        return Variable._apply(lambda a: other - a, self)

    def __mul__(self, other):
        return self._binop(other, lambda a, b: a * b)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, lambda a, b: a / b)

    def __rtruediv__(self, other):
        return Variable._apply(lambda a: other / a, self)

    def __neg__(self):
        return Variable._apply(lambda a: -a, self)

    def __pow__(self, p):
        return Variable._apply(lambda a: a ** p, self)

    def __getitem__(self, item):
        return Variable._apply(lambda a: a[item], self)


# ---------------------------------------------------------------------------
# math functions (reference: ``autograd.py`` module functions + math.scala)
# ---------------------------------------------------------------------------

def _unary(fn):
    def wrapper(v: Variable) -> Variable:
        return Variable._apply(fn, v)
    return wrapper


abs = _unary(jnp.abs)            # noqa: A001 - reference name
square = _unary(jnp.square)
sqrt = _unary(jnp.sqrt)
exp = _unary(jnp.exp)
log = _unary(jnp.log)
erf = _unary(jax.scipy.special.erf)
softsign = _unary(jax.nn.soft_sign)
softplus = _unary(jax.nn.softplus)
sigmoid = _unary(jax.nn.sigmoid)
tanh = _unary(jnp.tanh)
relu = _unary(jax.nn.relu)


def mean(v: Variable, axis: int = 0, keepdims: bool = False) -> Variable:
    """Mean over NON-batch axis ``axis`` (reference semantics: axis counts
    from the first non-batch dim... axis 0 == batch in keras-1; we follow
    the reference's ``mean(x, axis)`` where axis includes batch)."""
    return Variable._apply(
        lambda a: jnp.mean(a, axis=axis, keepdims=keepdims), v)


def sum(v: Variable, axis: int = 0, keepdims: bool = False) -> Variable:  # noqa: A001
    return Variable._apply(
        lambda a: jnp.sum(a, axis=axis, keepdims=keepdims), v)


def clip(v: Variable, min: float, max: float) -> Variable:  # noqa: A002
    return Variable._apply(lambda a: jnp.clip(a, min, max), v)


def pow(v: Variable, p: float) -> Variable:  # noqa: A001
    return v ** p


def maximum(a: Variable, b) -> Variable:
    if isinstance(b, Variable):
        return Variable._apply(jnp.maximum, a, b)
    return Variable._apply(lambda x: jnp.maximum(x, b), a)


def mm(a: Variable, b: Variable, axes: Optional[List[int]] = None
       ) -> Variable:
    """Batch matrix multiply with optional contraction axes (reference:
    ``autograd.mm``)."""
    if axes is None:
        return Variable._apply(jnp.matmul, a, b)
    ax1, ax2 = axes
    return Variable._apply(
        lambda x, y: _tensordot_batch(x, y, ax1, ax2), a, b)


def _tensordot_batch(x, y, ax1, ax2):
    # contract ax1 of x with ax2 of y, batching over axis 0
    return jax.vmap(lambda xx, yy: jnp.tensordot(
        xx, yy, axes=([ax1 - 1], [ax2 - 1])))(x, y)


def batch_dot(a: Variable, b: Variable, axes: Sequence[int] = (1, 1)
              ) -> Variable:
    """reference: ``batch_dot`` (keras-1 semantics)."""
    ax1, ax2 = axes
    return Variable._apply(
        lambda x, y: _tensordot_batch(x, y, ax1, ax2), a, b)


def l2_normalize(v: Variable, axis: int = -1) -> Variable:
    return Variable._apply(
        lambda a: a / jnp.maximum(
            jnp.linalg.norm(a, axis=axis, keepdims=True), 1e-12), v)


def expand_dims(v: Variable, axis: int) -> Variable:
    return Variable._apply(lambda a: jnp.expand_dims(a, axis), v)


def stack(vars: Sequence[Variable], axis: int = 1) -> Variable:
    return Variable._apply(lambda *xs: jnp.stack(xs, axis=axis), *vars)


def contiguous(v: Variable) -> Variable:
    return v  # jax arrays are always "contiguous"


# ---------------------------------------------------------------------------
# CustomLoss (reference: ``CustomLoss`` in autograd.py + CustomLossWithVariable)
# ---------------------------------------------------------------------------

class CustomLoss:
    """Build a loss function from a Variable expression over (y_true,
    y_pred) Variables; usable directly in ``model.compile(loss=...)``."""

    def __init__(self, loss: Variable, y_true: Variable, y_pred: Variable):
        self._model = Model(input=[y_true.node, y_pred.node],
                            output=loss.node, name="custom_loss")

    def __call__(self, y_true, y_pred):
        out = self._model._forward({}, [y_true, y_pred], training=False,
                                   rng=None, collect=None)
        return jnp.mean(out)
