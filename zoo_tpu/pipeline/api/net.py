"""``Net`` — the one-stop foreign/native model loading facade.

Rebuild of the reference's ``pyzoo/zoo/pipeline/api/net/net.py`` (class
``Net`` with ``load_bigdl`` / ``load`` / ``load_torch`` / ``load_tf`` /
``load_caffe`` / ``load_keras`` static loaders). Each loader returns a
zoo model (:class:`KerasNet`) that predicts/fine-tunes on TPU like any
natively-built model; the heavy lifting lives in the per-format modules
(``models.caffe_loader``, ``pipeline.api.onnx``, ``bridges.*``)."""

from __future__ import annotations

from typing import Optional, Sequence


class Net:
    """Static loaders for models from other frameworks/formats."""

    @staticmethod
    def load(path: str):
        """Load a natively-saved zoo model (reference ``Net.load`` loads a
        BigDL Model; here the pickled KerasNet from ``model.save``)."""
        from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
        return KerasNet.load(path)

    load_bigdl = load

    @staticmethod
    def load_caffe(def_path: Optional[str], model_path: str):
        """Load a Caffe model (reference ``Net.load_caffe``; Scala
        ``CaffeLoader.loadCaffe`` ``models/caffe/CaffeLoader.scala:718``)."""
        from zoo_tpu.models.caffe_loader import load_caffe
        return load_caffe(def_path, model_path)

    @staticmethod
    def load_torch(module_or_path, example_inputs: Sequence):
        """Load a PyTorch ``nn.Module`` (or a ``torch.save`` file path) by
        tracing it to a JAX graph (reference ``Net.load_torch`` ships a
        pickled module through jep; ``TorchModel.scala:34``)."""
        from zoo_tpu.bridges.fx_bridge import torch_to_graph_net
        if isinstance(module_or_path, str):
            import torch
            module_or_path = torch.load(module_or_path, weights_only=False)
        return torch_to_graph_net(module_or_path, example_inputs)

    @staticmethod
    def load_tf(path: str, signature: str = "serving_default"):
        """Load a TF SavedModel / frozen graph for inference (reference
        ``Net.load_tf`` → ``TFNet.scala:56``)."""
        from zoo_tpu.bridges.tf_graph import load_saved_model
        return load_saved_model(path, signature=signature)

    @staticmethod
    def load_onnx(path_or_bytes):
        """Load an ONNX model (reference ``onnx_loader.py:1``)."""
        from zoo_tpu.pipeline.api.onnx.onnx_loader import load_onnx
        return load_onnx(path_or_bytes)

    @staticmethod
    def load_keras(model):
        """Convert an in-memory tf.keras model (reference ``Net.load_keras``
        converts a keras definition+weights json/hdf5 pair)."""
        from zoo_tpu.bridges.keras_bridge import convert_keras_model
        return convert_keras_model(model)
