"""NNFrames: Spark-ML-pipeline-style Estimator/Transformer wrappers.

Rebuild of the reference's NNFrames API
(``pyzoo/zoo/pipeline/nnframes/nn_classifier.py:139`` ``NNEstimator`` /
``NNModel`` / ``NNClassifier`` / ``NNClassifierModel``; Scala
``pipeline/nnframes/``): ``NNEstimator(model, criterion).setBatchSize(n)
.setMaxEpoch(e).fit(df)`` returns an ``NNModel`` transformer whose
``transform(df)`` appends a ``prediction`` column. The reference rides
Spark DataFrames; here the same estimator/transformer contract runs over
pandas DataFrames (and XShards of them) feeding the jitted sharded step —
the SURVEY §7.1 translation-table north star (``cluster_mode`` decides the
mesh, not the API).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np


def _to_matrix(df, cols: Sequence[str]) -> np.ndarray:
    """Feature columns → (n, d) float matrix; array-valued cells (the
    Spark Vector role) flatten in order."""
    parts = []
    for c in cols:
        v = df[c].to_numpy()
        if v.dtype == object:  # column of arrays/lists
            v = np.stack([np.asarray(e, np.float32).reshape(-1)
                          for e in v])
        else:
            v = v.astype(np.float32).reshape(len(v), -1)
        parts.append(v)
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)


def _featurize(df, cols: Sequence[str], preprocessing) -> np.ndarray:
    """Feature columns → model input. Without a chain: flat numeric
    matrix (the Spark Vector role). With a ``sample_preprocessing``
    chain: the chain maps each cell of the single feature column to a
    feature carrying ``tensor`` (or a transformed ``image``), preserving
    tensor shape for conv models."""
    if preprocessing is None:
        return _to_matrix(df, cols)
    if len(cols) != 1:
        raise ValueError(
            "sample_preprocessing requires a single feature column; got "
            f"{list(cols)}")
    xs = []
    for cell in df[cols[0]]:
        f = preprocessing(cell)
        if isinstance(f, dict):
            t = f.get("tensor", f.get("image"))
        else:
            t = f
        xs.append(np.asarray(t, np.float32))
    return np.stack(xs)


class NNEstimator:
    """Builder-style estimator (set* methods mirror the Spark-ML params)."""

    def __init__(self, model, criterion: str = "mse",
                 features_col: str = "features", label_col: str = "label",
                 sample_preprocessing=None):
        self.model = model
        self.criterion = criterion
        self.features_col = [features_col] if isinstance(features_col, str) \
            else list(features_col)
        self.label_col = label_col
        self.batch_size = 32
        self.max_epoch = 1
        self.learning_rate: Optional[float] = None
        self.optim_method = "adam"
        self.caching_sample = True
        self.sample_preprocessing = sample_preprocessing

    # -- Spark-ML style setters -------------------------------------------
    def setFeaturesCol(self, col: Union[str, Sequence[str]]):
        self.features_col = [col] if isinstance(col, str) else list(col)
        return self

    def setLabelCol(self, col: str):
        self.label_col = col
        return self

    def setBatchSize(self, n: int):
        self.batch_size = int(n)
        return self

    def setMaxEpoch(self, n: int):
        self.max_epoch = int(n)
        return self

    def setLearningRate(self, lr: float):
        self.learning_rate = float(lr)
        return self

    def setOptimMethod(self, name: str):
        self.optim_method = name
        return self

    def setCachingSample(self, flag: bool):
        self.caching_sample = bool(flag)
        return self

    def setSamplePreprocessing(self, chain):
        """Per-cell transform chain applied to the (single) feature
        column before stacking — the reference's image-pipeline entry
        (``NNEstimator(..., sample_preprocessing=ChainedPreprocessing(
        [RowToImageFeature(), ImageResize(...), ..., ImageMatToTensor()
        ]))``). The chain's output feature must carry ``tensor`` (or
        leave ``image`` as the tensor)."""
        self.sample_preprocessing = chain
        return self

    # -- fit ---------------------------------------------------------------
    def _compile(self):
        if self.model.loss_fn is None:
            from zoo_tpu.pipeline.api.keras import optimizers as zopt

            opt = {"adam": zopt.Adam, "sgd": zopt.SGD,
                   "rmsprop": zopt.RMSprop}[self.optim_method.lower()]
            kwargs = {} if self.learning_rate is None \
                else {"lr": self.learning_rate}
            self.model.compile(optimizer=opt(**kwargs),
                               loss=self.criterion)

    def _unpack(self, df):
        from zoo_tpu.orca.data.shard import LocalXShards
        from zoo_tpu.orca.data.spark import (
            is_spark_dataframe,
            spark_dataframe_to_shards,
        )

        if is_spark_dataframe(df):
            # Spark ML contract (reference nn_classifier.py:139): the
            # executors write shard files; this process loads its slice
            # and proceeds over pandas (no driver collect)
            import pandas as pd

            label = ([self.label_col]
                     if self.label_col in df.columns else [])
            shards = spark_dataframe_to_shards(
                df, self.features_col, label)
            frames = []
            for s in shards.collect():
                x = np.asarray(s["x"])
                if len(self.features_col) == 1:
                    d = {self.features_col[0]: list(x)}
                else:
                    d = {c: x[:, i]
                         for i, c in enumerate(self.features_col)}
                if "y" in s:
                    d[self.label_col] = np.asarray(s["y"])
                frames.append(pd.DataFrame(d))
            if not frames:
                raise ValueError(
                    "this process received no rows from the Spark "
                    "DataFrame (empty partitions, or more JAX processes "
                    "than non-empty partitions — repartition the "
                    "DataFrame to at least process_count parts)")
            df = pd.concat(frames, ignore_index=True)
        if isinstance(df, LocalXShards):
            import pandas as pd

            df = pd.concat(df.collect(), ignore_index=True)
        x = _featurize(df, self.features_col, self.sample_preprocessing)
        y = df[self.label_col].to_numpy() if self.label_col in df else None
        return df, x, y

    def fit(self, df) -> "NNModel":
        df, x, y = self._unpack(df)
        if y is None:
            raise ValueError(f"label column {self.label_col!r} not in df")
        self._compile()
        y = self._prepare_labels(y)
        self.model.fit(x, y, batch_size=self.batch_size,
                       nb_epoch=self.max_epoch, verbose=0)
        return self._make_model()

    def _prepare_labels(self, y):
        return y.astype(np.float32).reshape(len(y), -1)

    def _make_model(self) -> "NNModel":
        return NNModel(self.model, features_col=self.features_col,
                       sample_preprocessing=self.sample_preprocessing)


class NNModel:
    """Transformer: appends ``prediction`` to the DataFrame (reference
    ``NNModel.transform``)."""

    prediction_col = "prediction"

    def __init__(self, model, features_col: Sequence[str] = ("features",),
                 sample_preprocessing=None):
        self.model = model
        self.features_col = list(features_col)
        self.batch_size = 256
        self.sample_preprocessing = sample_preprocessing

    def setFeaturesCol(self, col: Union[str, Sequence[str]]):
        self.features_col = [col] if isinstance(col, str) else list(col)
        return self

    def setBatchSize(self, n: int):
        self.batch_size = int(n)
        return self

    def setPredictionCol(self, col: str):
        self.prediction_col = col
        return self

    def setSamplePreprocessing(self, chain):
        self.sample_preprocessing = chain
        return self

    def _predict(self, df) -> np.ndarray:
        x = _featurize(df, self.features_col, self.sample_preprocessing)
        return self.model.predict(x, batch_size=self.batch_size)

    def transform(self, df):
        from zoo_tpu.orca.data.shard import LocalXShards

        if isinstance(df, LocalXShards):
            return df.transform_shard(self.transform)
        out = df.copy()
        preds = self._predict(df)
        out[self.prediction_col] = (preds[:, 0] if preds.ndim == 2
                                    and preds.shape[1] == 1
                                    else list(preds))
        return out


class NNClassifier(NNEstimator):
    """Classifier flavor: integer labels, argmax prediction (reference
    ``NNClassifier`` — labels are 1-based there via Spark-ML convention;
    0-based here, documented)."""

    def __init__(self, model, criterion: str =
                 "sparse_categorical_crossentropy",
                 features_col: str = "features", label_col: str = "label",
                 sample_preprocessing=None):
        super().__init__(model, criterion, features_col, label_col,
                         sample_preprocessing=sample_preprocessing)

    def _prepare_labels(self, y):
        return y.astype(np.int32)

    def _make_model(self) -> "NNClassifierModel":
        return NNClassifierModel(
            self.model, features_col=self.features_col,
            sample_preprocessing=self.sample_preprocessing)


class NNClassifierModel(NNModel):
    def transform(self, df):
        from zoo_tpu.orca.data.shard import LocalXShards

        if isinstance(df, LocalXShards):
            return df.transform_shard(self.transform)
        out = df.copy()
        probs = self._predict(df)
        out[self.prediction_col] = np.argmax(probs, axis=-1) \
            if probs.ndim > 1 and probs.shape[-1] > 1 \
            else (probs.reshape(-1) > 0.5).astype(np.int32)
        return out
