"""DataFrame-based image loading for NNFrames pipelines.

Rebuild of the reference's ``NNImageReader.readImages``
(``pyzoo/zoo/pipeline/nnframes/nn_image_reader.py:25`` — reads an image
directory into a Spark DataFrame with one ``image`` struct column) and
``RowToImageFeature`` (``pyzoo/zoo/feature/common.py`` role: the first
link of an NNEstimator ``sample_preprocessing`` chain, turning a
DataFrame cell back into an ``ImageFeature``).

TPU-native shape: the "DataFrame" is pandas (the NNFrames adapter's
in-process table form; Spark DataFrames enter through the gated
``orca.data.spark`` ingestion instead), and the ``image`` column holds
decoded HWC BGR uint8 ndarrays — cv2.imread semantics, matching the
reference's OpenCV CvType rows — plus ``origin`` (uri) and, when the
directory layout is ``path/<class>/*.jpg``, an integer ``label`` column.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from zoo_tpu.feature.image import ImageFeature, ImagePreprocessing, ImageSet


class NNImageReader:
    """reference: ``nn_image_reader.py:25`` (Spark-free equivalent)."""

    @staticmethod
    def readImages(path: str, sc=None, minPartitions: int = 1,
                   resizeH: int = -1, resizeW: int = -1,
                   image_codec: int = -1,
                   with_label: Optional[bool] = None):
        """Read a directory/glob of images into a pandas DataFrame with
        columns ``image`` (HWC BGR uint8 ndarray), ``origin`` (file
        path) and — for a ``path/<class>/*`` layout — ``label``.

        ``sc``/``minPartitions``/``image_codec`` are accepted for
        reference signature compatibility and ignored (no Spark in this process; pass the
        DataFrame to ``NNEstimator.fit`` directly). ``with_label=None``
        auto-detects the class-subdirectory layout.
        """
        import os

        import pandas as pd

        if with_label is None:
            # class-dir layout only if some non-hidden subdir actually
            # holds images — a stray '.ipynb_checkpoints'/'__MACOSX'
            # must not flip a flat directory into (empty) labeled mode
            from zoo_tpu.feature.image import _IMG_EXTS

            def _has_images(d):
                return os.path.isdir(d) and any(
                    f.lower().endswith(_IMG_EXTS)
                    for f in os.listdir(d))

            with_label = os.path.isdir(path) and any(
                not d.startswith((".", "__"))
                and _has_images(os.path.join(path, d))
                for d in os.listdir(path))
        iset = ImageSet.read(path, with_label=with_label,
                             resize_height=resizeH, resize_width=resizeW)
        if not iset.features:
            raise FileNotFoundError(f"no readable images under {path!r}")
        data = {"image": [f["image"] for f in iset.features],
                "origin": [f.get("uri") for f in iset.features]}
        if with_label:
            data["label"] = np.asarray(
                [f.get("label", -1) for f in iset.features], np.int32)
        df = pd.DataFrame(data)
        df.attrs["label_map"] = getattr(iset, "label_map", {})
        return df


class RowToImageFeature(ImagePreprocessing):
    """First link of an image ``sample_preprocessing`` chain: turns a
    DataFrame cell (ndarray, or an ImageFeature already) into a fresh
    ``ImageFeature`` so downstream transformers can mutate freely
    (reference: ``RowToImageFeature`` over the Spark image struct)."""

    def __call__(self, cell):
        if isinstance(cell, ImageFeature):
            return ImageFeature(image=np.asarray(cell["image"]).copy(),
                                label=cell.get("label"),
                                uri=cell.get("uri"))
        return ImageFeature(image=np.asarray(cell).copy())
