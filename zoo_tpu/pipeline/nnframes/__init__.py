from zoo_tpu.pipeline.nnframes.nn_classifier import (  # noqa: F401
    NNClassifier,
    NNClassifierModel,
    NNEstimator,
    NNModel,
)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel"]
