from zoo_tpu.pipeline.nnframes.nn_classifier import (  # noqa: F401
    NNClassifier,
    NNClassifierModel,
    NNEstimator,
    NNModel,
)
from zoo_tpu.pipeline.nnframes.nn_image_reader import (  # noqa: F401
    NNImageReader,
    RowToImageFeature,
)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader", "RowToImageFeature"]
