from zoo_tpu.pipeline.inference.inference_model import InferenceModel

__all__ = ["InferenceModel"]
