"""InferenceModel — thread-safe multi-backend inference holder.

Rebuild of ``pipeline/inference/InferenceModel.scala`` (657 LoC; loads
BigDL/Caffe/OpenVINO/TF/Torch with ``supported_concurrent_num`` controlling
a blocking pool of model copies) and the Python wrapper
``pyzoo/zoo/pipeline/inference/inference_model.py:24``.

On TPU there are no model copies: a jitted XLA executable is pure and
reentrant, so ``supported_concurrent_num`` maps to a semaphore that bounds
in-flight predict calls (protecting HBM, not correctness). Loading AOT
warm-compiles the forward for the configured batch size (the reference's
OpenVINO ahead-of-time IR compile maps to ``jit(...).lower().compile()``).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import numpy as np


class InferenceModel:
    def __init__(self, supported_concurrent_num: int = 1):
        self._sem = threading.Semaphore(supported_concurrent_num)
        self.supported_concurrent_num = supported_concurrent_num
        self._model = None
        self._batch_size: Optional[int] = None

    # -- loaders (reference: doLoad* family) -------------------------------
    def load_keras(self, model, batch_size: Optional[int] = None,
                   example_input: Optional[Sequence[np.ndarray]] = None):
        """Hold a zoo_tpu Keras-facade model; AOT-compile at ``batch_size``
        when example input is derivable."""
        self._model = model
        self._batch_size = batch_size
        if batch_size and model.params is not None:
            shapes = model._built_shapes or model._input_shapes()
            if example_input is None and shapes:
                example_input = [np.zeros((batch_size,) + tuple(s[1:]),
                                          np.float32) for s in shapes]
            if example_input is not None:
                model.predict(example_input if len(example_input) > 1
                              else example_input[0],
                              batch_size=batch_size)  # warm compile
        return self

    def load(self, path: str, batch_size: Optional[int] = None,  # zoo-lint: config-parse
             quantize: bool = False):
        """Load a full serialized zoo model (reference: ``doLoadBigDL``;
        ``quantize=True`` is the int8 path, reference
        ``doLoadOpenVINOInt8`` ``InferenceModel.scala:283``). The
        inference loaders quantize in ``auto`` mode: int8 is kept only
        when it measures faster than the float forward on the current
        backend (override with ``ZOO_INT8_MODE=force|off``)."""
        from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
        model = KerasNet.load(path)
        if quantize:
            model = quantize_model(
                model,
                mode=os.environ.get("ZOO_INT8_MODE") or "auto")
        return self.load_keras(model, batch_size=batch_size)

    def load_caffe(self, def_path: Optional[str], model_path: str,
                   batch_size: Optional[int] = None):
        """reference: ``doLoadCaffe`` — Caffe deploy net + weights."""
        from zoo_tpu.models.caffe_loader import load_caffe
        return self.load_keras(load_caffe(def_path, model_path),
                               batch_size=batch_size)

    def load_onnx(self, path_or_bytes, batch_size: Optional[int] = None):
        """ONNX graph as an inference holder (reference ONNX loader)."""
        from zoo_tpu.pipeline.api.onnx.onnx_loader import load_onnx
        return self.load_keras(load_onnx(path_or_bytes),
                               batch_size=batch_size)

    def load_encrypted(self, path: str, secret: str, salt: str,  # zoo-lint: config-parse
                       key_len: int = 128, mode: str = "cbc",
                       batch_size: Optional[int] = None,
                       quantize: bool = False):
        """Load an encrypted-at-rest zoo model (reference:
        ``doLoadEncrypted*`` via ``EncryptSupportive.scala:27``). The file
        is decrypted in memory only — plaintext never touches disk."""
        import cloudpickle

        from zoo_tpu.ppml.crypto import EncryptSupportive
        blob = EncryptSupportive.decrypt_file(path, secret, salt,
                                              key_len=key_len, mode=mode)
        model = cloudpickle.loads(blob)
        if quantize:
            model = quantize_model(
                model,
                mode=os.environ.get("ZOO_INT8_MODE") or "auto")
        return self.load_keras(model, batch_size=batch_size)

    def load_tf(self, model_or_path, batch_size: Optional[int] = None,
                example_inputs=None, signature: str = "serving_default"):
        """Load a TF model for inference (reference: ``doLoadTF`` /
        ``TFNet.scala:56``): a SavedModel directory path, a tf.keras model,
        or any tf.function-able callable. The graph is frozen and
        interpreted in JAX (``zoo_tpu.bridges.tf_graph``)."""
        from zoo_tpu.bridges.tf_graph import (
            TFGraphWrapper,
            convert_tf_callable,
            load_saved_model,
        )

        if isinstance(model_or_path, str):
            g = load_saved_model(model_or_path, signature=signature)
        else:
            if example_inputs is None:
                raise ValueError("pass example_inputs= for non-SavedModel "
                                 "TF objects")
            g = convert_tf_callable(model_or_path, list(example_inputs))
        self._model = TFGraphWrapper(g)
        self._batch_size = batch_size
        return self

    def load_torch(self, torch_model, input_shape=None,
                   batch_size: Optional[int] = None,
                   example_inputs=None, input_dtype="float32"):
        """reference: ``doLoadPyTorch`` — via the torch.export fx bridge
        (arbitrary forward graphs, not just Sequential). Pass
        ``example_inputs`` (list of arrays, batch dim included) for
        multi-input or non-float models, or ``input_dtype`` (e.g. "int32"
        for embedding-first nets) with ``input_shape``."""
        import numpy as _np

        from zoo_tpu.bridges.fx_bridge import torch_to_graph_net
        if example_inputs is None:
            if input_shape is None:
                raise ValueError("pass input_shape= or example_inputs=")
            example_inputs = [_np.zeros((2,) + tuple(input_shape),
                                        _np.dtype(input_dtype))]
        return self.load_keras(
            torch_to_graph_net(torch_model, list(example_inputs)),
            batch_size=batch_size)

    # -- inference ---------------------------------------------------------
    def predict(self, x, batch_size: Optional[int] = None) -> np.ndarray:
        """Blocking-pool predict (reference: ``doPredict`` takes a copy from
        the blocking queue; here the semaphore bounds concurrency)."""
        if self._model is None:
            raise RuntimeError("no model loaded")
        bs = batch_size or self._batch_size or 256
        with self._sem:
            return self._model.predict(x, batch_size=bs)

    @property
    def model(self):
        return self._model


def save_encrypted(model, path: str, secret: str, salt: str,
                   key_len: int = 128, mode: str = "cbc"):
    """Serialize a zoo model encrypted at rest (counterpart of
    ``InferenceModel.load_encrypted``; reference ``EncryptSupportive``).
    Serialization happens in memory — plaintext never touches disk."""
    from zoo_tpu.ppml.crypto import EncryptSupportive
    enc = (EncryptSupportive.encrypt_bytes_with_aes_cbc if mode == "cbc"
           else EncryptSupportive.encrypt_bytes_with_aes_gcm)
    with open(path, "wb") as f:
        f.write(enc(model.to_bytes(), secret, salt, key_len))
    return path


# auto mode keeps int8 only when it beats the float forward by this
# factor (also the reference point bench.py reports the chosen path
# against — one constant, one decision rule)
INT8_MIN_SPEEDUP = 1.05


def _copy_tree(tree):
    """Shallow-copy every nested dict of a params tree (leaf arrays
    shared) — enough to undo the in-place W → W_q/W_scale rewrite."""
    if isinstance(tree, dict):
        return {k: _copy_tree(v) for k, v in tree.items()}
    return tree


#: (architecture fingerprint, sample shapes, sample_batch) -> (path,
#: speedup). An auto verdict is a property of the architecture and the
#: backend, not the weight values — re-quantizing the same topology
#: (rolling reloads, A/B replicas, per-request model copies) reuses the
#: measured verdict instead of paying the microbench again.
_AUTO_VERDICT_CACHE: dict = {}


def _model_fingerprint(model) -> tuple:
    """Architecture identity for the auto-verdict cache: layer types in
    order plus every param leaf's path/shape/dtype (values excluded)."""
    import jax

    layers = tuple(type(l).__name__ for l in getattr(model, "layers", ()))
    leaves = tuple(
        (jax.tree_util.keystr(kp), tuple(v.shape), str(v.dtype))
        for kp, v in jax.tree_util.tree_leaves_with_path(model.params))
    return (layers, leaves)


def _publish_quant_path(path: str, speedup: Optional[float]) -> None:
    """Record every quantize_model decision in the scrape — the chosen
    path is never silent. Prior verdicts flip to 0 (info-gauge style,
    like ``zoo_registry_version_info``) so exactly one series is 1."""
    from zoo_tpu.obs.metrics import gauge

    fam = gauge(
        "zoo_quant_path_info",
        "int8 quantization path chosen by quantize_model (1 = current "
        "verdict) with the measured int8/float speedup as a label "
        "(\"-\" when the mode skipped the microbench)",
        labels=("path", "speedup"))
    for child in fam.children():
        child.set(0.0)
    fam.labels(path=path,
               speedup="-" if speedup is None else f"{speedup:.3f}"
               ).set(1.0)


def _time_forward(model, xs, reps: int = 3) -> float:
    """Samples/s of the jitted forward over device-warm inputs (compile
    excluded by a warm-up call). Module-level so tests can stub it."""
    import time

    import jax

    step = model._build_pred_step()
    params = model.params
    out = step(params, *xs)
    jax.block_until_ready(out)
    n = xs[0].shape[0] * reps
    t0 = time.perf_counter()
    for _ in range(reps):
        out = step(params, *xs)
    jax.block_until_ready(out)
    return n / max(time.perf_counter() - t0, 1e-9)


def _apply_int8(model):
    from zoo_tpu.ops.pallas.quant import (
        quantize_conv_weights,
        quantize_int8,
    )
    from zoo_tpu.pipeline.api.keras.layers.convolutional import (
        Convolution2D,
    )
    from zoo_tpu.pipeline.api.keras.layers.core import Dense

    dense_keys = {model._key_of(l) for l in model.layers
                  if isinstance(l, Dense)}
    conv_keys = {model._key_of(l) for l in model.layers
                 if isinstance(l, Convolution2D)}

    def walk(tree):
        for key, val in list(tree.items()):
            if isinstance(val, dict):
                if key in dense_keys and "W" in val:
                    w = val.pop("W")
                    w_q, w_scale = quantize_int8(w, axis=0)
                    val["W_q"], val["W_scale"] = w_q, w_scale
                elif key in conv_keys and "W" in val:
                    w = val.pop("W")
                    val["W_q"], val["W_scale"] = quantize_conv_weights(w)
                else:
                    walk(val)

    walk(model.params)
    model._jit_pred = model._jit_eval = model._jit_train = None
    model._quantized = True  # inference-only: fit() refuses cleanly


def quantize_model(model, mode: Optional[str] = None,  # zoo-lint: config-parse
                   min_speedup: float = INT8_MIN_SPEEDUP,
                   sample_batch: int = 8):
    """Post-training int8 quantization of every Dense and Conv2D weight
    (per-output-channel symmetric); the forward then runs the int8 MXU
    matmul / int8 conv (``ops/pallas/quant.py``). TPU equivalent of the
    reference's OpenVINO int8 IR path (``doLoadOpenVINOInt8``) and the
    VNNI int8 story — whose headline use is conv-net inference
    (SSD/VGG, ``wp-bigdl.md:192-196``).

    ``mode`` (default ``"force"`` for API compatibility; the
    ``InferenceModel`` loaders default to ``"auto"``. Env
    ``ZOO_INT8_MODE`` fills in an UNSPECIFIED mode only — an explicit
    ``mode=`` argument always wins, so programmatic callers cannot be
    silently redirected by ambient environment):

    * ``"force"`` — always quantize (the historical behavior);
    * ``"off"`` — return the model unquantized;
    * ``"auto"`` — **measure-or-fallback**: quantize, microbench the
      int8 forward against the float forward at ``sample_batch`` rows,
      and KEEP int8 only if it wins by ``min_speedup``; otherwise
      restore the float weights (BENCH_r05 measured int8 ResNet-50
      *0.974x* the bf16 path — slower — on the current backend, so an
      unconditional int8 serve path was a pessimization).

    The chosen path is recorded on the model as ``_quant_path``
    (``"int8"`` / ``"bf16-fallback"`` / ``"bf16"``) with the measured
    ratio in ``_quant_speedup`` when auto measured one.
    """
    import logging

    mode = mode or os.environ.get("ZOO_INT8_MODE") or "force"
    if mode not in ("auto", "force", "off"):
        raise ValueError(f"unknown int8 mode {mode!r} "
                         "(expected auto|force|off)")
    if mode == "off":
        model._quant_path = "bf16"
        _publish_quant_path("bf16", None)
        return model
    if model.params is None:
        raise ValueError("model must be built before quantization")
    if mode == "force":
        _apply_int8(model)
        model._quant_path = "int8"
        _publish_quant_path("int8", None)
        return model

    # auto: measure int8 against float on this backend, fall back when
    # it doesn't win
    shapes = getattr(model, "_built_shapes", None) or \
        model._input_shapes()
    xs = None
    if shapes:
        try:
            xs = [np.zeros((sample_batch,) + tuple(s[1:]), np.float32)
                  for s in shapes]
        except TypeError:
            xs = None
    if xs is None:
        # nothing to measure with: behave like force (documented)
        _apply_int8(model)
        model._quant_path = "int8"
        _publish_quant_path("int8", None)
        return model
    key = (_model_fingerprint(model),
           tuple(tuple(x.shape) for x in xs), float(min_speedup))
    cached = _AUTO_VERDICT_CACHE.get(key)
    if cached is not None:
        # same architecture + sample shapes on this backend: replay the
        # verdict instead of re-benching (common under rolling reloads)
        path, speedup = cached
        model._quant_speedup = speedup
        model._quant_path = path
        if path == "int8":
            _apply_int8(model)
        _publish_quant_path(path, speedup)
        return model
    float_rate = _time_forward(model, xs)
    saved = _copy_tree(model.params)
    _apply_int8(model)
    int8_rate = _time_forward(model, xs)
    speedup = int8_rate / max(float_rate, 1e-9)
    model._quant_speedup = speedup
    if speedup >= min_speedup:
        model._quant_path = "int8"
        _AUTO_VERDICT_CACHE[key] = ("int8", speedup)
        _publish_quant_path("int8", speedup)
        return model
    # int8 loses on this backend: restore the float weights
    model.params = saved
    model._jit_pred = model._jit_eval = model._jit_train = None
    model._quantized = False
    model._quant_path = "bf16-fallback"
    _AUTO_VERDICT_CACHE[key] = ("bf16-fallback", speedup)
    _publish_quant_path("bf16-fallback", speedup)
    logging.getLogger(__name__).info(
        "int8 quantization measured %.3fx the float forward (< %.2fx "
        "threshold) on this backend — serving the bf16 path instead",
        speedup, min_speedup)
    return model
