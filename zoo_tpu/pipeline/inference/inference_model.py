"""InferenceModel — thread-safe multi-backend inference holder.

Rebuild of ``pipeline/inference/InferenceModel.scala`` (657 LoC; loads
BigDL/Caffe/OpenVINO/TF/Torch with ``supported_concurrent_num`` controlling
a blocking pool of model copies) and the Python wrapper
``pyzoo/zoo/pipeline/inference/inference_model.py:24``.

On TPU there are no model copies: a jitted XLA executable is pure and
reentrant, so ``supported_concurrent_num`` maps to a semaphore that bounds
in-flight predict calls (protecting HBM, not correctness). Loading AOT
warm-compiles the forward for the configured batch size (the reference's
OpenVINO ahead-of-time IR compile maps to ``jit(...).lower().compile()``).
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np


class InferenceModel:
    def __init__(self, supported_concurrent_num: int = 1):
        self._sem = threading.Semaphore(supported_concurrent_num)
        self.supported_concurrent_num = supported_concurrent_num
        self._model = None
        self._batch_size: Optional[int] = None

    # -- loaders (reference: doLoad* family) -------------------------------
    def load_keras(self, model, batch_size: Optional[int] = None,
                   example_input: Optional[Sequence[np.ndarray]] = None):
        """Hold a zoo_tpu Keras-facade model; AOT-compile at ``batch_size``
        when example input is derivable."""
        self._model = model
        self._batch_size = batch_size
        if batch_size and model.params is not None:
            shapes = model._built_shapes or model._input_shapes()
            if example_input is None and shapes:
                example_input = [np.zeros((batch_size,) + tuple(s[1:]),
                                          np.float32) for s in shapes]
            if example_input is not None:
                model.predict(example_input if len(example_input) > 1
                              else example_input[0],
                              batch_size=batch_size)  # warm compile
        return self

    def load(self, path: str, batch_size: Optional[int] = None):
        """Load a full serialized zoo model (reference: ``doLoadBigDL``)."""
        from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
        return self.load_keras(KerasNet.load(path), batch_size=batch_size)

    def load_tf(self, model_or_path, batch_size: Optional[int] = None,
                example_inputs=None, signature: str = "serving_default"):
        """Load a TF model for inference (reference: ``doLoadTF`` /
        ``TFNet.scala:56``): a SavedModel directory path, a tf.keras model,
        or any tf.function-able callable. The graph is frozen and
        interpreted in JAX (``zoo_tpu.bridges.tf_graph``)."""
        from zoo_tpu.bridges.tf_graph import (
            TFGraphWrapper,
            convert_tf_callable,
            load_saved_model,
        )

        if isinstance(model_or_path, str):
            g = load_saved_model(model_or_path, signature=signature)
        else:
            if example_inputs is None:
                raise ValueError("pass example_inputs= for non-SavedModel "
                                 "TF objects")
            g = convert_tf_callable(model_or_path, list(example_inputs))
        self._model = TFGraphWrapper(g)
        self._batch_size = batch_size
        return self

    def load_torch(self, torch_model, input_shape=None,
                   batch_size: Optional[int] = None,
                   example_inputs=None, input_dtype="float32"):
        """reference: ``doLoadPyTorch`` — via the torch.export fx bridge
        (arbitrary forward graphs, not just Sequential). Pass
        ``example_inputs`` (list of arrays, batch dim included) for
        multi-input or non-float models, or ``input_dtype`` (e.g. "int32"
        for embedding-first nets) with ``input_shape``."""
        import numpy as _np

        from zoo_tpu.bridges.fx_bridge import torch_to_graph_net
        if example_inputs is None:
            if input_shape is None:
                raise ValueError("pass input_shape= or example_inputs=")
            example_inputs = [_np.zeros((2,) + tuple(input_shape),
                                        _np.dtype(input_dtype))]
        return self.load_keras(
            torch_to_graph_net(torch_model, list(example_inputs)),
            batch_size=batch_size)

    # -- inference ---------------------------------------------------------
    def predict(self, x, batch_size: Optional[int] = None) -> np.ndarray:
        """Blocking-pool predict (reference: ``doPredict`` takes a copy from
        the blocking queue; here the semaphore bounds concurrency)."""
        if self._model is None:
            raise RuntimeError("no model loaded")
        bs = batch_size or self._batch_size or 256
        with self._sem:
            return self._model.predict(x, batch_size=bs)

    @property
    def model(self):
        return self._model
