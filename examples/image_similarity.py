"""Image similarity search (reference: ``apps/image-similarity``
notebook — extract deep features with a zoo image model, rank a gallery
by cosine similarity to a query).

Run: python examples/image_similarity.py [--gallery 48]
"""

import argparse

import numpy as np


def make_gallery(n, size=64, seed=0):
    """Images of colored shapes; same shape+hue = same semantic group."""
    rs = np.random.RandomState(seed)
    imgs, groups = [], []
    for i in range(n):
        group = i % 4
        img = rs.rand(size, size, 3).astype(np.float32) * 0.15
        hue = np.zeros(3, np.float32)
        hue[group % 3] = 1.0
        c = size // 2 + rs.randint(-6, 7, 2)
        half = 8 + (4 if group >= 2 else 0)
        img[c[0] - half:c[0] + half, c[1] - half:c[1] + half] += hue * 0.8
        imgs.append(np.clip(img, 0, 1))
        groups.append(group)
    return np.stack(imgs), np.asarray(groups)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gallery", type=int, default=48)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.models.image import squeezenet
    from zoo_tpu.pipeline.api.keras.engine.topology import Model

    init_orca_context(cluster_mode="local")
    gallery, groups = make_gallery(args.gallery)

    # feature extractor: the classifier minus its softmax head (the
    # reference pulled an intermediate layer of a pretrained model)
    clf = squeezenet(class_num=16, input_shape=(64, 64, 3))
    # walk back from the softmax output: softmax <- GAP <- logits-conv;
    # the GAP node is the pooled deep-feature tensor
    feat_tensor = clf.outputs[0].inbound[0]
    extractor = Model(input=clf.inputs[0], output=feat_tensor)
    extractor.params = clf.build()

    feats = np.array(extractor.predict(gallery, batch_size=16))
    feats = feats.reshape(len(gallery), -1)
    feats /= np.linalg.norm(feats, axis=1, keepdims=True) + 1e-9

    query_idx = 0
    sims = feats @ feats[query_idx]
    order = np.argsort(-sims)
    top = [i for i in order if i != query_idx][:5]
    hit = np.mean([groups[i] == groups[query_idx] for i in top])
    print(f"query group {groups[query_idx]}; top-5 groups: "
          f"{[int(groups[i]) for i in top]} (precision {hit:.2f})")
    # random-feature extractor on structured images: color/shape energy
    # still clusters — top-5 should beat the 25% group base rate
    assert hit >= 0.4, hit
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
