"""3D (volumetric) image augmentation + Conv3D classification
(reference role: the ``image-augmentation-3d`` app over the Scala
``feature/image3d`` transforms).

Synthetic "scan" volumes containing either a bright sphere (class 0) or
a bright bar (class 1) run through the 3D preprocessing chain
(RandomCrop3D → Rotate3D), then a tiny Convolution3D classifier trains
on the augmented patches and is evaluated on clean center-cropped
volumes.

Run: python examples/image_augmentation_3d.py [--epochs 14]
"""

import argparse
import random

import numpy as np


def make_volumes(n, size=20, seed=0):
    rs = np.random.RandomState(seed)
    vols, labels = [], []
    for i in range(n):
        v = rs.rand(size, size, size).astype(np.float32) * 0.2
        c = rs.randint(2)
        cz, cy, cx = rs.randint(6, size - 6, 3)
        r = rs.randint(3, 5)
        if c == 0:  # sphere
            z, y, x = np.ogrid[:size, :size, :size]
            mask = (z - cz) ** 2 + (y - cy) ** 2 + (x - cx) ** 2 <= r * r
        else:       # long thin bar along z
            mask = np.zeros((size, size, size), bool)
            mask[max(cz - 2 * r, 0):cz + 2 * r,
                 cy - 1:cy + 1, cx - 1:cx + 1] = True
        v[mask] = 0.9 + 0.05 * rs.randn(int(mask.sum()))
        vols.append(v)
        labels.append(c)
    return vols, np.asarray(labels, np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=14)
    ap.add_argument("--volumes", type=int, default=200)
    args = ap.parse_args()

    from zoo_tpu.feature.common import ChainedPreprocessing
    from zoo_tpu.feature.image import ImageSet
    from zoo_tpu.feature.image3d import (
        CenterCrop3D,
        RandomCrop3D,
        Rotate3D,
    )
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import (
        Convolution3D,
        Dense,
        Flatten,
    )

    init_orca_context(cluster_mode="local")
    try:
        random.seed(0)  # RandomCrop3D draws from stdlib random
        vols, labels = make_volumes(args.volumes)
        train = ImageSet.from_arrays(vols, labels)
        # the 3D augmentation chain (reference: Crop3D/Rotate3D over
        # TImageFeature3D)
        aug = ChainedPreprocessing([
            RandomCrop3D(patch_size=(16, 16, 16)),
            Rotate3D(rotation_angles=(0.0, 0.0, 0.2)),
        ])
        train = train.transform(aug)
        x = np.stack(train.get_image())[..., None]
        y = np.asarray(train.get_label(), np.int32)
        print(f"augmented train patches: {x.shape}")

        m = Sequential()
        m.add(Convolution3D(8, 3, 3, 3, subsample=(2, 2, 2),
                            activation="relu", dim_ordering="tf",
                            input_shape=(16, 16, 16, 1)))
        m.add(Convolution3D(16, 3, 3, 3, subsample=(2, 2, 2),
                            activation="relu", dim_ordering="tf"))
        m.add(Flatten())
        m.add(Dense(2, activation="softmax"))
        m.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        m.fit(x, y, batch_size=16, nb_epoch=args.epochs, verbose=0)

        tv, tl = make_volumes(32, seed=9)
        test = ImageSet.from_arrays(tv, tl)
        test = test.transform(CenterCrop3D(patch_size=(16, 16, 16)))
        xt = np.stack(test.get_image())[..., None]
        res = m.evaluate(xt, tl, batch_size=16)
        print(f"held-out: {res}")
        assert res["accuracy"] >= 0.75, res
        print("OK")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
