"""Quantized-inference walkthrough (reference role: the OpenVINO int8
calibrate-and-serve flow of ``zoo/examples/vnni/openvino`` — here the
int8 path is the Pallas int8 MXU kernel behind ``quantize_model``).

Flow: train a small classifier → wrap in ``InferenceModel`` → snapshot
fp32 predictions → int8-quantize → compare accuracy drift and latency,
then demonstrate the encrypted-checkpoint load path (PPML role) also
serving quantized.

Run: python examples/quantized_inference.py [--epochs 3] [--rows 2048]
"""

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--rows", type=int, default=2048)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense, Dropout
    from zoo_tpu.pipeline.inference.inference_model import (
        InferenceModel,
        quantize_model,
    )

    init_orca_context(cluster_mode="local")
    try:
        rs = np.random.RandomState(0)
        x = rs.randn(args.rows, 16).astype(np.float32)
        w_true = rs.randn(16, 4)
        y = np.argmax(x @ w_true + 0.1 * rs.randn(args.rows, 4), axis=1)

        model = Sequential()
        model.add(Dense(64, input_shape=(16,), activation="relu"))
        model.add(Dropout(0.1))
        model.add(Dense(4, activation="softmax"))
        model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy")
        model.fit(x, y, batch_size=128, nb_epoch=args.epochs, verbose=0)

        im = InferenceModel()
        im.load_keras(model)
        xt = rs.randn(512, 16).astype(np.float32)
        yt = np.argmax(xt @ w_true, axis=1)

        def bench(tag):
            im.predict(xt[:64])  # warm/compile
            t0 = time.perf_counter()
            preds = im.predict(xt)
            dt = time.perf_counter() - t0
            acc = float((np.argmax(preds, 1) == yt).mean())
            print(f"{tag}: accuracy={acc:.3f} "
                  f"latency={dt * 1e3:.1f}ms/512 rows")
            return preds, acc

        preds32, acc32 = bench("fp32")
        # snapshot the fp32 model encrypted BEFORE quantizing (int8
        # weights don't re-quantize)
        import tempfile

        from zoo_tpu.pipeline.inference.inference_model import (
            save_encrypted,
        )

        enc_path = tempfile.mktemp(suffix=".enc")
        save_encrypted(model, enc_path, secret="demo-secret",
                       salt="demo-salt")

        quantize_model(model)  # per-channel int8 weights, int8 MXU matmul
        preds8, acc8 = bench("int8")
        drift = float(np.abs(preds32 - preds8).max())
        print(f"max |fp32 - int8| prediction drift: {drift:.4f}")
        assert acc8 >= acc32 - 0.05, "int8 accuracy fell more than 5pp"
        print("int8 accuracy within 5pp of fp32 — OK")

        # PPML role: the encrypted-checkpoint path also serves quantized
        im_enc = InferenceModel()
        im_enc.load_encrypted(enc_path, secret="demo-secret",
                              salt="demo-salt")
        quantize_model(im_enc.model)
        enc_preds = im_enc.predict(xt[:32])
        np.testing.assert_allclose(enc_preds, preds8[:32], rtol=1e-4,
                                   atol=1e-5)
        print("encrypted load + int8 predictions match — OK")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
