"""QA answer ranking with KNRM (reference:
``pyzoo/zoo/examples/qaranker/qa_ranker.py``: TextSet relations + KNRM,
pairwise training, listwise NDCG/MAP evaluation).

Run: python examples/qa_ranking_knrm.py [--epochs 12]
"""

import argparse

import numpy as np


def make_qa_corpus(n_q=40, n_cand=6, seed=0):
    """Questions about a topic word; the right answer repeats it."""
    rs = np.random.RandomState(seed)
    topics = ("planet star comet orbit moon galaxy nebula quasar "
              "meteor cluster dust cloud").split()
    filler = ("the a is of about tell me what how why fact info "
              "detail item thing").split()
    questions, answers, relations = [], [], []
    aid = 0
    for qid in range(n_q):
        topic = topics[qid % len(topics)]
        q_text = f"tell me about {topic} " + " ".join(
            rs.choice(filler, 3))
        questions.append((f"q{qid}", q_text))
        pos = rs.randint(0, n_cand)
        for c in range(n_cand):
            if c == pos:
                text = (f"{topic} " * 2 + " ".join(rs.choice(filler, 4)))
                label = 1
            else:
                other = topics[(qid + 1 + c) % len(topics)]
                text = (f"{other} " + " ".join(rs.choice(filler, 5)))
                label = 0
            answers.append((f"a{aid}", text))
            relations.append((f"q{qid}", f"a{aid}", label))
            aid += 1
    return questions, answers, relations


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.feature.text import TextFeature, TextSet
    from zoo_tpu.models.ranking import KNRM

    init_orca_context(cluster_mode="local")
    q_len, a_len = 8, 10
    questions, answers, relations = make_qa_corpus()

    q_set = TextSet([TextFeature(t, uri=u) for u, t in questions])
    a_set = TextSet([TextFeature(t, uri=u) for u, t in answers])
    q_set.tokenize().normalize()
    a_set.tokenize().normalize()
    # shared vocabulary: index answers with the question corpus map
    q_set.word2idx(max_words_num=500)
    a_set.word2idx(existing_map=q_set.get_word_index())
    q_set.shape_sequence(len=q_len)
    a_set.shape_sequence(len=a_len)
    vocab = max(q_set.get_word_index().values()) + 2

    pairs = TextSet.from_relation_pairs(relations, q_set, a_set)
    x, y = pairs.to_arrays()
    cut = int(0.8 * len(x))

    model = KNRM(text1_length=q_len, text2_length=a_len,
                 vocab_size=vocab, embed_size=32)
    model.compile(optimizer="adam", loss="binary_crossentropy")
    model.fit(x[:cut], y[:cut].astype(np.float32)[:, None],
              batch_size=64, nb_epoch=args.epochs, verbose=0)

    # listwise evaluation: rank each question's candidates
    lists = TextSet.from_relation_lists(relations, q_set, a_set)
    hits, total = 0, 0
    for f in lists.features:
        scores = np.asarray(model.predict(
            np.asarray(f["indexedTokens"], np.int32),
            batch_size=len(f["label"]))).ravel()
        if f["label"][int(np.argmax(scores))] == 1:
            hits += 1
        total += 1
    p_at_1 = hits / total
    print(f"P@1 over {total} queries: {p_at_1:.2f} "
          f"(random would be {1 / 6:.2f})")
    assert p_at_1 > 0.4, p_at_1
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
