"""Cluster Serving round trip (reference: the ClusterServingGuide quick
start): model → InferenceModel → redis-protocol serving worker →
InputQueue/OutputQueue client → HTTP frontend.

Uses the embedded RESP server; point ``--redis-host/--redis-port`` at a
real Redis to run the identical wire against it.

Run: python examples/cluster_serving_roundtrip.py
"""

import argparse
import json
import time
import urllib.request

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--redis-host", default=None)
    ap.add_argument("--redis-port", type=int, default=None)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.inference import InferenceModel
    from zoo_tpu.serving import (
        ClusterServing,
        EmbeddedRedis,
        FrontEnd,
        InputQueue,
        OutputQueue,
    )

    init_orca_context(cluster_mode="local")
    embedded = None
    if args.redis_host is None:
        embedded = EmbeddedRedis().start()
        host, port = "127.0.0.1", embedded.port
    else:
        host, port = args.redis_host, args.redis_port or 6379

    m = Sequential()
    m.add(Dense(8, input_shape=(4,), activation="relu"))
    m.add(Dense(3, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.build()
    im = InferenceModel(supported_concurrent_num=2)
    im.load_keras(m)

    serving = ClusterServing(im, redis_host=host, redis_port=port).start()
    iq = InputQueue(host=host, port=port)
    oq = OutputQueue(host=host, port=port)

    x = np.random.RandomState(0).randn(4).astype(np.float32)
    iq.enqueue("example-1", t=x)
    out = "[]"
    for _ in range(200):
        out = oq.query("example-1")
        if not isinstance(out, str):
            break
        time.sleep(0.02)
    print("queue result:", np.asarray(out))

    sync = iq.predict(x)
    print("sync predict:", np.asarray(sync))

    fe = FrontEnd(serving, iq).start()
    body = json.dumps({"instances": [{"t": x.tolist()}]}).encode()
    req = urllib.request.Request(
        f"http://{fe.host}:{fe.port}/predict", data=body,
        headers={"Content-Type": "application/json"})
    resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
    val = json.loads(json.loads(resp["predictions"][0])["value"])
    print("http predict:", np.asarray(val["data"]).reshape(val["shape"]))
    met = json.loads(urllib.request.urlopen(
        f"http://{fe.host}:{fe.port}/metrics", timeout=30).read())
    print("metrics:", met)

    fe.stop()
    serving.stop()
    if embedded is not None:
        embedded.stop()
    stop_orca_context()
    print("serving example OK")


if __name__ == "__main__":
    main()
