"""Image classification via ParquetDataset + ResNet (reference:
``apps/dogs-vs-cats`` transfer-learning notebook).

With ``--data <dir>`` pointing at an image folder (``dir/<class>/*.jpg``)
the real images are packed to parquet and trained; otherwise a synthetic
two-class image set (bright vs dark blobs) runs the identical pipeline:
write_from_directory/write_ndarrays → ParquetDataset → ImageSet transforms
→ ResNet-18 fit with the mixed-bf16 policy.

Run: python examples/dogs_vs_cats_resnet.py [--data dir] [--epochs 3]
"""

import argparse
import tempfile

import numpy as np


def synthetic_images(n=256, hw=32, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 2, n)
    base = rs.rand(n, hw, hw, 3).astype(np.float32)
    images = np.where(labels[:, None, None, None] == 1,
                      base * 0.5 + 0.5, base * 0.5)
    return images.astype(np.float32), labels.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.orca.data.parquet_dataset import (
        ParquetDataset,
        write_ndarrays,
    )
    from zoo_tpu.models.image import resnet18
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    init_orca_context(cluster_mode="local")
    out = tempfile.mkdtemp() + "/images_parquet"
    if args.data:
        import os

        from zoo_tpu.orca.data.parquet_dataset import write_from_directory
        classes = sorted(os.listdir(args.data))
        write_from_directory(args.data, {c: i for i, c in
                                         enumerate(classes)}, out)
        raise SystemExit("real-image decode path: wire cv2.imdecode over "
                         "the 'image' column, then continue as below")
    images, labels = synthetic_images()
    write_ndarrays(images, labels, out)

    data = ParquetDataset.read_as_arrays(out)
    x, y = data["image"], data["label"].astype(np.int32)
    print("parquet roundtrip:", x.shape, y.shape)

    m = resnet18(class_num=2, input_shape=x.shape[1:])
    m.compile(optimizer=Adam(lr=0.001),
              loss="sparse_categorical_crossentropy",
              metrics=["accuracy"], dtype_policy="mixed_bfloat16")
    hist = m.fit(x, y, batch_size=args.batch_size, nb_epoch=args.epochs,
                 verbose=0)
    print("train loss:", [round(v, 4) for v in hist["loss"]])
    res = m.evaluate(x, y, batch_size=args.batch_size)
    print("eval:", {k: round(v, 4) for k, v in res.items()})
    stop_orca_context()
    assert res["accuracy"] > 0.7
    print("dogs-vs-cats example OK")


if __name__ == "__main__":
    main()
