"""Credit-card fraud detection (reference: ``apps/fraud-detection``
notebook — imbalanced-class fraud classification on the public
creditcard.csv, with resampling transformers and precision/recall
evaluation).

The dataset here is synthetic with the same shape as the Kaggle set
(PCA-style V1..V28 features + Amount, ~0.6% positive class) so the
example is hermetic; point ``--csv`` at the real creditcard.csv to run it
on the actual data. Mirrors the app's pipeline: standardize → rebalance
the training split (minority oversampling) → train an MLP classifier →
report AUC + precision/recall at a threshold.

Run: python examples/fraud_detection.py [--rows 20000]
"""

import argparse

import numpy as np


def synthetic_transactions(n, seed=0):
    """~0.6% fraud; fraud shifts a few feature means (separable-ish)."""
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 29).astype(np.float32)
    y = (rs.rand(n) < 0.006).astype(np.int32)
    shift = np.zeros(29, np.float32)
    shift[[1, 3, 7, 11]] = 2.2
    x[y == 1] += shift + 0.3 * rs.randn(int(y.sum()), 29)
    x[:, -1] = np.abs(x[:, -1]) * 88.0  # Amount-like column
    return x, y


def rebalance(x, y, ratio=0.25, seed=1):
    """Oversample the minority class to ``ratio`` of the majority count
    (the app's resampling transformer role)."""
    rs = np.random.RandomState(seed)
    pos = np.where(y == 1)[0]
    neg = np.where(y == 0)[0]
    if len(pos) == 0:
        raise ValueError(
            "training split contains no fraud rows — nothing to "
            "oversample; use more rows (--rows) or a dataset slice that "
            "includes positives")
    need = int(len(neg) * ratio)
    picked = rs.choice(pos, size=need, replace=True)
    idx = np.concatenate([neg, picked])
    rs.shuffle(idx)
    return x[idx], y[idx]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=20000)
    ap.add_argument("--csv", default=None,
                    help="path to the real creditcard.csv (optional)")
    ap.add_argument("--epochs", type=int, default=4)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense, Dropout

    init_orca_context(cluster_mode="local")

    if args.csv:
        import pandas as pd
        df = pd.read_csv(args.csv)
        y = df["Class"].to_numpy().astype(np.int32)
        x = df.drop(columns=["Class", "Time"], errors="ignore") \
            .to_numpy().astype(np.float32)
    else:
        x, y = synthetic_transactions(args.rows)

    # standardize, then split before resampling (never resample eval data)
    x = (x - x.mean(0)) / (x.std(0) + 1e-7)
    n_train = int(0.8 * len(x))
    x_tr, y_tr = rebalance(x[:n_train], y[:n_train])
    x_te, y_te = x[n_train:], y[n_train:]

    m = Sequential()
    m.add(Dense(32, activation="relu", input_shape=(x.shape[1],)))
    m.add(Dropout(0.1))
    m.add(Dense(16, activation="relu"))
    m.add(Dense(1, activation="sigmoid"))
    est = Estimator.from_keras(m)
    m.compile(optimizer="adam", loss="binary_crossentropy",
              metrics=["auc"])
    est.fit({"x": x_tr, "y": y_tr.astype(np.float32).reshape(-1, 1)},
            epochs=args.epochs, batch_size=256)

    scores = m.predict(x_te, batch_size=1024).reshape(-1)
    metrics = m.evaluate(x_te, y_te.astype(np.float32).reshape(-1, 1),
                         batch_size=1024)
    pred = (scores > 0.5).astype(np.int32)
    tp = int(((pred == 1) & (y_te == 1)).sum())
    fp = int(((pred == 1) & (y_te == 0)).sum())
    fn = int(((pred == 0) & (y_te == 1)).sum())
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    print(f"test AUC={metrics.get('auc', float('nan')):.4f} "
          f"precision={precision:.3f} recall={recall:.3f} "
          f"(tp={tp} fp={fp} fn={fn})")
    assert metrics.get("auc", 0) > 0.9, "fraud model failed to separate"
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
