"""Image-classification inference from a pre-trained TensorFlow model
(reference: ``apps/tfnet`` notebook — load an InceptionV1 slim checkpoint
with TFNet and classify images; ``TFNet.scala:56`` runs the frozen graph
in-process).

TPU-native path: the TF SavedModel is ingested by the frozen-graph → JAX
interpreter (``bridges/tf_graph.py``) through ``InferenceModel.load_tf``
— the graph then runs as XLA on the TPU, no TensorFlow in the serving
process. The "pre-trained checkpoint" here is a small CNN trained
in-process so the example is hermetic; point ``--saved_model`` at a real
export (e.g. slim InceptionV1) to reproduce the app.

Run: python examples/tfnet_image_inference.py
"""

import argparse
import json
import os
import tempfile

import numpy as np

CLASS_INDEX = {0: "cat", 1: "dog", 2: "fox", 3: "owl"}


def make_pretrained_saved_model(path):
    """Stand-in for downloading a slim checkpoint: a tiny tf.keras CNN
    'pre-trained' on colored-square classes, exported as SavedModel.
    Returns (images, TF's own predictions on them) — the fidelity
    reference for the ingested graph."""
    import tensorflow as tf

    tf.keras.utils.set_random_seed(0)
    rs = np.random.RandomState(0)
    x = rs.rand(256, 32, 32, 3).astype(np.float32) * 0.2
    y = rs.randint(0, 4, 256)
    for i, cls in enumerate(y):
        x[i, 8:24, 8:24, cls % 3] += 0.7
        if cls == 3:
            x[i, 8:24, 8:24, :] += 0.4
    m = tf.keras.Sequential([
        tf.keras.layers.Input(shape=(32, 32, 3)),
        tf.keras.layers.Conv2D(8, 3, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Conv2D(16, 3, padding="same", activation="relu"),
        tf.keras.layers.GlobalAveragePooling2D(),
        tf.keras.layers.Dense(4, activation="softmax"),
    ])
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.fit(x, y, epochs=8, batch_size=64, verbose=0)
    tf.saved_model.save(m, path)
    return x[:8], m.predict(x[:8], verbose=0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--saved_model", default=None,
                    help="existing TF SavedModel dir (else one is built)")
    ap.add_argument("--image_size", type=int, default=32,
                    help="input H=W the saved model expects "
                         "(e.g. 224 for slim InceptionV1)")
    ap.add_argument("--top_k", type=int, default=2)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.inference import InferenceModel

    init_orca_context(cluster_mode="local")

    if args.saved_model:
        sm_dir, imgs, tf_probs = args.saved_model, None, None
    else:
        sm_dir = os.path.join(tempfile.mkdtemp(prefix="tfnet_"), "sm")
        imgs, tf_probs = make_pretrained_saved_model(sm_dir)

    # the TFNet role: frozen TF graph -> XLA, inside the inference holder
    model = InferenceModel(supported_concurrent_num=2)
    model.load_tf(sm_dir)

    if imgs is None:
        rs = np.random.RandomState(0)
        s = args.image_size
        imgs = rs.rand(8, s, s, 3).astype(np.float32)
        tf_probs = None
    probs = np.asarray(model.predict(imgs))
    top = np.argsort(-probs, axis=-1)[:, :args.top_k]
    for i, row in enumerate(top):
        decoded = [(CLASS_INDEX.get(int(c), str(int(c))),
                    round(float(probs[i, c]), 3)) for c in row]
        print(f"image {i}: {json.dumps(decoded)}")
    if tf_probs is not None:
        # the contract under test is INGESTION FIDELITY: the XLA-run
        # graph must reproduce TF's own outputs (model quality is not
        # the example's business)
        err = float(np.abs(probs - tf_probs).max())
        print(f"max |ingested - tensorflow| on probabilities: {err:.5f}")
        assert err < 1e-3, "ingested graph disagrees with TF"
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
