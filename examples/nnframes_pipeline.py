"""NNFrames ML-pipeline workflow (reference:
``pyzoo/zoo/examples/nnframes`` — NNClassifier over a DataFrame with
Spark-ML builder params, transform appends a prediction column).

Run: python examples/nnframes_pipeline.py [--epochs 8]
"""

import argparse

import numpy as np
import pandas as pd


def make_frame(n=1200, seed=0):
    rs = np.random.RandomState(seed)
    df = pd.DataFrame({
        "age": rs.uniform(18, 80, n).astype(np.float32),
        "income": rs.uniform(10, 200, n).astype(np.float32),
        "visits": rs.randint(0, 50, n).astype(np.float32),
    })
    score = (df.income / 200 + df.visits / 50 - (df.age - 18) / 124
             + 0.1 * rs.randn(n))
    df["label"] = (score > score.median()).astype(np.int64)
    return df


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.nnframes import NNClassifier

    init_orca_context(cluster_mode="local")
    df = make_frame()
    cut = int(0.8 * len(df))
    train, test = df.iloc[:cut], df.iloc[cut:].reset_index(drop=True)

    net = Sequential()
    net.add(Dense(16, activation="relu", input_shape=(3,)))
    net.add(Dense(2, activation="softmax"))

    clf = (NNClassifier(net)
           .setFeaturesCol(["age", "income", "visits"])
           .setLabelCol("label")
           .setBatchSize(128)
           .setMaxEpoch(args.epochs)
           .setLearningRate(3e-3)
           .setOptimMethod("adam"))
    model = clf.fit(train)

    scored = model.transform(test)
    acc = float((scored["prediction"] == test["label"]).mean())
    print(scored.head(5).to_string())
    print("holdout accuracy:", round(acc, 3))
    assert acc > 0.8, acc
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
