"""Variational autoencoder (reference: the three
``apps/using_variational_autoencoder*`` notebooks): functional graph with
the GaussianSampler reparameterization layer, a KL+reconstruction loss
via the autograd DSL, digit-like synthetic images, and latent-space
interpolation.

Run: python examples/variational_autoencoder.py [--epochs 8]
"""

import argparse

import numpy as np


def make_blobs(n=2048, size=12, seed=0):
    """Images with one bright blob; position is the generative factor."""
    rs = np.random.RandomState(seed)
    cx, cy = rs.uniform(2, size - 2, n), rs.uniform(2, size - 2, n)
    g = np.arange(size)
    xx, yy = np.meshgrid(g, g)
    imgs = np.exp(-(((xx[None] - cx[:, None, None]) ** 2
                     + (yy[None] - cy[:, None, None]) ** 2) / 4.0))
    return imgs.reshape(n, -1).astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--latent", type=int, default=2)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
    from zoo_tpu.pipeline.api.keras.layers import (
        Dense,
        GaussianSampler,
        merge,
    )

    init_orca_context(cluster_mode="local")
    size = 12
    x = make_blobs(size=size)
    d = size * size

    inp = Input(shape=(d,), name="image")
    h = Dense(64, activation="relu")(inp)
    z_mean = Dense(args.latent, name="z_mean")(h)
    z_logv = Dense(args.latent, name="z_logv")(h)
    z = GaussianSampler()([z_mean, z_logv])
    dh = Dense(64, activation="relu")(z)
    recon = Dense(d, activation="sigmoid", name="recon")(dh)
    # fold the KL term into an extra output so the standard loss API
    # carries it: kl_out = concat(mean, logv) scored by a custom loss
    kl_out = merge([z_mean, z_logv], mode="concat", name="kl")

    vae = Model(input=inp, output=[recon, kl_out])

    import jax.numpy as jnp

    def kl_loss(y_true, y_pred):
        mean, logv = jnp.split(y_pred, 2, axis=-1)
        return 0.5 * jnp.mean(jnp.sum(
            jnp.square(mean) + jnp.exp(logv) - 1.0 - logv, axis=-1))

    vae.compile(optimizer="adam",
                loss=["binary_crossentropy", kl_loss],
                loss_weights=[d, 0.5])
    dummy_kl = np.zeros((len(x), 2 * args.latent), np.float32)
    h = vae.fit(x, [x, dummy_kl], batch_size=128, nb_epoch=args.epochs,
                verbose=0)
    print("loss:", round(h["loss"][0], 3), "->", round(h["loss"][-1], 3))
    assert h["loss"][-1] < h["loss"][0]

    recon_out, _ = vae.predict(x[:256], batch_size=256)
    err = float(np.mean((np.asarray(recon_out) - x[:256]) ** 2))
    print("reconstruction mse:", round(err, 5))
    assert err < 0.03

    # latent space is informative: z_mean should predict blob position
    encoder = Model(input=inp, output=z_mean)
    encoder.params = vae.params  # shared graph params
    zs = np.asarray(encoder.predict(x[:512], batch_size=256))
    g = np.arange(size)
    xs_, ys_ = np.meshgrid(g, g)
    cx = (x[:512].reshape(-1, size, size) * xs_).sum((1, 2)) / \
        x[:512].reshape(-1, size, size).sum((1, 2))
    corr = np.abs(np.corrcoef(zs.T, cx[None])[:-1, -1]).max()
    print("max |corr(z, blob_x)|:", round(float(corr), 3))
    assert corr > 0.5
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
