"""AutoXGBoost hyperparameter search (reference:
``pyzoo/zoo/examples/orca/automl/autoxgboost_regressor.py``): search the
boosted-tree knobs with the AutoML engine, refit the best config, and
compare against an untuned model.

Run: python examples/auto_xgboost_regression.py [--samples 8]
"""

import argparse

import numpy as np


def make_regression(n=2000, d=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    y = (np.sin(x[:, 0]) + 0.5 * x[:, 1] ** 2 + x[:, 2] * x[:, 3]
         + 0.1 * rs.randn(n)).astype(np.float32)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=8)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.automl import hp
    from zoo_tpu.orca.automl.xgboost import AutoXGBoost, XGBoostRegressor

    init_orca_context(cluster_mode="local")
    x, y = make_regression()
    cut = int(0.8 * len(x))
    train, val = (x[:cut], y[:cut]), (x[cut:], y[cut:])

    base = XGBoostRegressor(n_estimators=10, max_depth=2)
    base.fit(*train)
    base_mse = base.evaluate(*val)["mse"]

    auto = AutoXGBoost(task="regression", metric="mse")
    auto.fit(train, validation_data=val,
             search_space={"n_estimators": hp.choice([25, 50, 100]),
                           "max_depth": hp.choice([3, 5, 7]),
                           "learning_rate": hp.loguniform(0.03, 0.3)},
             n_sampling=args.samples)
    tuned_mse = auto.evaluate(*val)["mse"] if hasattr(auto, "evaluate") \
        else float(np.mean((auto.predict(val[0]) - val[1]) ** 2))
    print(f"untuned mse={base_mse:.4f}  tuned mse={tuned_mse:.4f}  "
          f"best={auto.best_config}")
    assert tuned_mse < base_mse
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
