"""Fine-tune an arbitrary PyTorch model on TPU via the torch.export
bridge (reference: ``pyzoo/zoo/examples/orca/learn/pytorch``; the jep
``TorchModel`` path ``TorchModel.scala:34`` carries "any torch module" —
here the module is traced to a JAX graph and trained with the Orca
PyTorch Estimator).

Run: python examples/torch_finetune.py [--epochs 3]
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()

    import torch
    import torch.nn as nn

    class SmallTransformerClassifier(nn.Module):
        """Multi-input (ids + mask) attention model — the shape of model
        the old Sequential-only bridge could not carry."""

        def __init__(self, vocab=200, dim=32, classes=2):
            super().__init__()
            self.emb = nn.Embedding(vocab, dim)
            self.attn = nn.MultiheadAttention(dim, 4, batch_first=True)
            self.norm = nn.LayerNorm(dim)
            self.head = nn.Linear(dim, classes)

        def forward(self, ids, mask):
            h = self.emb(ids)
            a, _ = self.attn(h, h, h,
                             key_padding_mask=(mask == 0))
            h = self.norm(h + a)
            pooled = (h * mask[..., None]).sum(1) / \
                mask.sum(1, keepdim=True).clamp(min=1)
            return self.head(pooled)

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.orca.learn.pytorch import Estimator

    init_orca_context(cluster_mode="local")
    rs = np.random.RandomState(0)
    n, seq = 256, 12
    ids = rs.randint(1, 200, size=(n, seq)).astype(np.int64)
    mask = np.ones((n, seq), np.float32)
    # class = whether token 7 appears — learnable from attention pooling
    y = (ids == 7).any(axis=1).astype(np.int64)

    tmodel = SmallTransformerClassifier()
    est = Estimator.from_torch(
        model=tmodel,
        optimizer=torch.optim.Adam(tmodel.parameters(), lr=3e-3),
        loss=nn.CrossEntropyLoss())
    est.fit({"x": [ids, mask], "y": y}, epochs=args.epochs, batch_size=32)
    res = est.evaluate({"x": [ids, mask], "y": y}, batch_size=64)
    print("train-set eval:", res)

    # weights round-trip back into torch (reference: TorchModel weight
    # write-back) — torch CPU logits match the TPU-trained model
    back = est.get_model()
    with torch.no_grad():
        t_logits = back(torch.from_numpy(ids[:8]),
                        torch.from_numpy(mask[:8])).numpy()
    j_logits = est.predict([ids[:8], mask[:8]], batch_size=8)
    err = float(np.max(np.abs(t_logits - np.asarray(j_logits))))
    print("torch-vs-jax max logit err:", err)
    assert err < 1e-2
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
