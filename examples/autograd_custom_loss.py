"""Autograd DSL: custom loss + Lambda-style variable math (reference:
``pyzoo/zoo/examples/autograd`` — ``custom.py`` builds a CustomLoss from
Variable expressions, ``customloss.py`` trains with it).

Fits a small regressor with a hand-built robust loss (mean absolute
error with an epsilon-insensitive zone expressed in Variable ops) and
compares against plain MSE on data with heavy-tailed label noise —
the robust loss should win on clean held-out MSE.

Run: python examples/autograd_custom_loss.py [--epochs 20]
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api import autograd as A
    from zoo_tpu.pipeline.api.autograd import CustomLoss, Variable
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    init_orca_context(cluster_mode="local")
    try:
        rs = np.random.RandomState(0)
        w_true = rs.randn(6, 1).astype(np.float32)
        x = rs.randn(512, 6).astype(np.float32)
        clean = x @ w_true
        # heavy-tailed corruption on 10% of labels
        noise = np.where(rs.rand(512, 1) < 0.1,
                         8.0 * rs.randn(512, 1), 0.02 * rs.randn(512, 1))
        y = (clean + noise).astype(np.float32)
        xt = rs.randn(128, 6).astype(np.float32)
        yt = xt @ w_true

        # epsilon-insensitive MAE, written in the Variable DSL exactly
        # like the reference's autograd example composes its loss
        y_true = Variable(input_shape=(1,))
        y_pred = Variable(input_shape=(1,))
        err = A.abs(y_true - y_pred)
        robust = A.mean(A.maximum(err - 0.05, 0.0), axis=1)
        robust_loss = CustomLoss(robust, y_true, y_pred)

        results = {}
        for tag, loss in (("mse", "mse"), ("robust", robust_loss)):
            m = Sequential()
            m.add(Dense(1, input_shape=(6,)))
            m.compile(optimizer=Adam(lr=0.05), loss=loss)
            m.fit(x, y, batch_size=64, nb_epoch=args.epochs, verbose=0)
            pred = np.asarray(m.predict(xt, batch_size=128))
            results[tag] = float(np.mean((pred - yt) ** 2))
            print(f"{tag:6s} loss -> clean held-out mse "
                  f"{results[tag]:.4f}")
        assert results["robust"] < results["mse"], results
        print("robust custom loss beats MSE under label corruption — OK")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
