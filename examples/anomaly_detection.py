"""Time-series anomaly detection (reference: ``apps/anomaly-detection``
notebook + ``pyzoo/zoo/examples/anomalydetection``): unroll a univariate
series into windows, train the stacked-LSTM AnomalyDetector to predict
the next value, flag the largest forecast errors as anomalies — then
cross-check with the Chronos ThresholdDetector.

Run: python examples/anomaly_detection.py [--epochs 5]
"""

import argparse

import numpy as np


def make_series(n=2000, n_anomalies=8, seed=0):
    rs = np.random.RandomState(seed)
    t = np.arange(n)
    base = (np.sin(t * 2 * np.pi / 50) + 0.5 * np.sin(t * 2 * np.pi / 113)
            + 0.05 * rs.randn(n)).astype(np.float32)
    idx = rs.choice(np.arange(100, n - 100), n_anomalies, replace=False)
    base[idx] += rs.choice([-1, 1], n_anomalies) * rs.uniform(
        2.0, 3.0, n_anomalies).astype(np.float32)
    return base, set(int(i) for i in idx)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--unroll", type=int, default=24)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.models.anomalydetection import AnomalyDetector

    init_orca_context(cluster_mode="local")
    series, truth = make_series()
    x, y = AnomalyDetector.unroll(series, args.unroll)
    cut = int(0.7 * len(x))

    model = AnomalyDetector(feature_shape=(args.unroll, 1))
    model.compile(optimizer="adam", loss="mse")
    model.fit(x[:cut], y[:cut], batch_size=128, nb_epoch=args.epochs,
              verbose=0)

    pred = np.asarray(model.predict(x, batch_size=256)).ravel()
    anoms = model.detect_anomalies(y, pred, anomaly_size=12)
    flagged = {a + args.unroll for a in anoms}  # window index -> series t
    hits = len(flagged & truth)
    print(f"LSTM detector: flagged {len(flagged)}, "
          f"true anomalies recovered {hits}/{len(truth)}")

    from zoo_tpu.chronos.detector.anomaly import ThresholdDetector
    td = ThresholdDetector()
    td.set_params(ratio=0.01)
    td.fit(y, pred)
    td_idx = set(int(i) + args.unroll for i in td.anomaly_indexes())
    print(f"ThresholdDetector: flagged {len(td_idx)}, "
          f"recovered {len(td_idx & truth)}/{len(truth)}")
    assert hits >= len(truth) // 2, (hits, truth)
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
