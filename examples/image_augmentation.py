"""Image augmentation pipeline (reference:
``pyzoo/zoo/examples/imageclassification`` preprocessing +
``apps/image-augmentation`` notebook): chain the ImageSet transformer
zoo — color jitter, random crop/flip/aspect scale — and feed the result
straight into training via ``ImageSet.to_arrays`` (swap in
``to_xshards()`` for the sharded estimator path).

Run: python examples/image_augmentation.py [--epochs 4]
"""

import argparse
import random

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.feature.common import ChainedPreprocessing
    from zoo_tpu.feature.image import (
        ImageBrightness,
        ImageChannelNormalize,
        ImageFeature,
        ImageHFlip,
        ImageMatToTensor,
        ImageRandomCrop,
        ImageRandomPreprocessing,
        ImageResize,
        ImageSet,
        ImageSetToSample,
    )
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Conv2D, Dense, Flatten

    init_orca_context(cluster_mode="local")
    random.seed(0)  # image transformers draw from the random module
    rs = np.random.RandomState(0)
    # two classes: bright blobs top-left vs bottom-right
    feats = []
    for i in range(240):
        img = (rs.rand(40, 40, 3) * 60).astype(np.uint8)
        label = i % 2
        y0, x0 = (4, 4) if label == 0 else (24, 24)
        img[y0:y0 + 12, x0:x0 + 12] += 150
        feats.append(ImageFeature(image=img, label=label,
                                  uri=f"img_{i}.jpg"))
    image_set = ImageSet(feats)

    augment = ChainedPreprocessing([
        ImageResize(36, 36),
        ImageRandomPreprocessing(ImageBrightness(-20, 20), 0.5),
        ImageRandomPreprocessing(ImageHFlip(), 0.0),  # flip would swap cls
        ImageRandomCrop(32, 32),
        ImageChannelNormalize(110.0, 110.0, 110.0, 60.0, 60.0, 60.0),
        ImageMatToTensor(format="NHWC"),
        ImageSetToSample(),
    ])
    transformed = image_set.transform(augment)
    x, y = transformed.to_arrays()
    print("augmented batch:", x.shape, "labels:", y.shape)

    m = Sequential()
    m.add(Conv2D(8, 3, 3, subsample=(2, 2), activation="relu",
                 border_mode="same", dim_ordering="tf",
                 input_shape=(32, 32, 3)))
    m.add(Conv2D(8, 3, 3, subsample=(2, 2), activation="relu",
                 border_mode="same", dim_ordering="tf"))
    m.add(Flatten())
    m.add(Dense(2, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    est = Estimator.from_keras(m)
    est.fit({"x": x, "y": y}, epochs=args.epochs, batch_size=48)
    res = est.evaluate({"x": x, "y": y}, batch_size=240)
    print("train-set accuracy:", res)
    assert res["accuracy"] > 0.9, res
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
