"""Elastic training: survive a mid-epoch worker fault (reference
semantics: ``Topology.scala:1255-1337`` — the InternalDistriOptimizer
catches any Throwable, reloads the latest checkpoint snapshot and
continues, bounded by ``bigdl.failure.retryTimes`` in a sliding window).

The rebuild's Orca Keras Estimator carries the same supervision
(``fit(..., max_failure_retries=...)``): with a ``model_dir`` configured,
a thrown step fault triggers restore-from-latest-checkpoint and the epoch
loop resumes. This script makes the story visible: train one clean epoch
(checkpoint written), inject a fault mid-epoch-2, and watch the
supervisor restore and finish — the loss trajectory continues downward
across the fault and the final model predicts fine.

Run: python examples/elastic_training.py [--epochs 4]
"""

import argparse
import tempfile

import numpy as np


class FaultInjector:
    """Wraps the jitted train step; raises once at a given global call
    (a stand-in for a real preempted host / failed collective)."""

    def __init__(self, real_step, fail_at_call: int):
        self.real_step = real_step
        self.calls = 0
        self.fail_at = fail_at_call
        self.fired = False

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.calls == self.fail_at and not self.fired:
            self.fired = True
            print(f"--- injected fault at step call {self.calls} ---")
            raise RuntimeError("injected worker fault")
        return self.real_step(*args, **kwargs)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    init_orca_context(cluster_mode="local")

    rs = np.random.RandomState(0)
    x = rs.randn(1024, 16).astype(np.float32)
    w = rs.randn(16, 1).astype(np.float32)
    data = {"x": x, "y": (x @ w + 0.05 * rs.randn(1024, 1)
                          ).astype(np.float32)}

    model = Sequential()
    model.add(Dense(32, input_shape=(16,), activation="relu"))
    model.add(Dense(1))
    model.compile(optimizer="adam", loss="mse")

    ckpt_dir = tempfile.mkdtemp(prefix="zoo_elastic_")
    est = Estimator.from_keras(model, model_dir=ckpt_dir)

    # epoch 1 clean: EveryEpoch checkpoint trigger writes a snapshot
    h1 = est.fit(data, epochs=1, batch_size=args.batch_size)
    print(f"epoch 1 clean, loss {h1['loss'][0]:.4f}, checkpoint at "
          f"{ckpt_dir}")

    # arm the injector on the compiled step, then train the remaining
    # epochs through the fault
    est.model.build()
    if est.model._jit_train is None:
        est.model._jit_train = est.model._build_train_step()
    injector = FaultInjector(est.model._jit_train, fail_at_call=3)
    est.model._jit_train = injector

    h2 = est.fit(data, epochs=args.epochs - 1,
                 batch_size=args.batch_size)
    assert injector.fired, "fault never fired — raise --epochs"
    print("supervisor restored from checkpoint and finished "
          f"{len(h2['loss'])} epochs; loss trajectory "
          f"{[round(v, 4) for v in h1['loss'] + h2['loss']]}")

    preds = np.asarray(est.predict(x[:8]))
    assert np.isfinite(preds).all()
    assert h2["loss"][-1] < h1["loss"][0], (h1["loss"], h2["loss"])
    stop_orca_context()
    print("Elastic training example OK")


if __name__ == "__main__":
    main()
