"""NCF recommendation end-to-end (reference: ``apps/recommendation-ncf``).

Reads MovieLens-format ``ratings.dat`` when ``--data`` points at one
(uid::mid::rating::ts), otherwise synthesizes an equivalent interaction
table — so the script always runs. Flow: csv → XShards → Orca Keras
Estimator fit → evaluate → predict, the SURVEY §7.3 minimum slice.

Run: python examples/ncf_movielens.py [--data ratings.dat] [--epochs 4]
"""

import argparse
import os
import tempfile

import numpy as np
import pandas as pd


def load_ratings(path=None, n_users=600, n_items=400, n=60_000, seed=0):
    if path and os.path.exists(path):
        df = pd.read_csv(path, sep="::", engine="python",
                         names=["user", "item", "rating", "ts"])
        return df[["user", "item", "rating"]]
    rs = np.random.RandomState(seed)
    user = rs.randint(0, n_users, n)
    item = rs.randint(0, n_items, n)
    # latent structure so the model has something to learn
    u_vec = rs.randn(n_users, 4)
    i_vec = rs.randn(n_items, 4)
    score = (u_vec[user] * i_vec[item]).sum(1)
    rating = np.clip(np.digitize(score, [-2, -0.7, 0.7, 2]) + 1, 1, 5)
    return pd.DataFrame({"user": user, "item": item, "rating": rating})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=512)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.orca.data.pandas import read_csv
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.models.recommendation import NeuralCF
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    init_orca_context(cluster_mode="local")
    df = load_ratings(args.data)
    df["label"] = df["rating"].astype("int32") - 1

    # csv → XShards (the orca data path)
    tmp = os.path.join(tempfile.mkdtemp(), "ratings.csv")
    df.to_csv(tmp, index=False)
    shards = read_csv(tmp, num_shards=4)

    model = NeuralCF(user_count=int(df.user.max()) + 1,
                     item_count=int(df.item.max()) + 1,
                     class_num=5, user_embed=32, item_embed=32,
                     hidden_layers=(64, 32), mf_embed=32)
    model.compile(optimizer=Adam(lr=0.001),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    est = Estimator.from_keras(model)
    hist = est.fit(shards, epochs=args.epochs, batch_size=args.batch_size,
                   feature_cols=["user", "item"], label_cols=["label"])
    print("train loss:", [round(v, 4) for v in hist["loss"]])
    res = est.evaluate(shards, batch_size=args.batch_size,
                       feature_cols=["user", "item"], label_cols=["label"])
    print("eval:", {k: round(v, 4) for k, v in res.items()})
    preds = est.predict(shards, feature_cols=["user", "item"])
    print("predictions:", preds.shape)
    stop_orca_context()
    assert hist["loss"][-1] < hist["loss"][0]
    print("NCF example OK")


if __name__ == "__main__":
    main()
