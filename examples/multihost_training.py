"""Multi-host data-parallel training, runnable on one dev box
(reference: the RayOnSpark multi-worker story — here the bootstrap
launcher spawns an N-process JAX cluster and each process feeds its own
data shards; on a real pod the same worker body runs once per host via
``scripts/run_tpu_pod.sh``).

Run: python examples/multihost_training.py [--nproc 2]

The script re-launches ITSELF under the supervisor: the parent spawns
``--nproc`` workers (fail-fast: an SPMD rank cannot rejoin a formed
cluster, so the whole group tears down on any crash), each worker joins
the cluster, keeps only its shard of the data, and trains the same model —
losses agree bit-for-bit across ranks because the global batch is
assembled from per-process shards inside ``fit``.
"""

import argparse
import os
import sys


def worker():
    import numpy as np

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # dev-box simulation: force the CPU platform before any device
        # query (some environments force-register an accelerator plugin
        # that ignores the env var; a real pod skips this branch)
        jax.config.update("jax_platforms", "cpu")
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.orca.data.shard import LocalXShards, shards_for_process
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    expected = int(os.environ["ZOO_NUM_PROCESSES"])
    init_orca_context(cluster_mode="tpu", num_nodes=expected)
    rank, world = jax.process_index(), jax.process_count()
    assert world == expected, (world, expected)

    # every process derives the same logical dataset, keeps its own part
    rs = np.random.RandomState(0)
    x = rs.randn(512, 16).astype(np.float32)
    y = (x @ rs.randn(16, 1)).astype(np.float32)
    shards = LocalXShards.partition({"x": x, "y": y}, num_shards=2 * world)
    mine = shards_for_process(shards)

    m = Sequential()
    m.add(Dense(32, activation="relu", input_shape=(16,)))
    m.add(Dense(1))
    m.compile(optimizer="adam", loss="mse")
    est = Estimator.from_keras(m)
    h = est.fit(mine, epochs=3, batch_size=64)  # 64 global, 64/world local
    print(f"rank {rank}/{world}: loss {h['loss'][0]:.4f} -> "
          f"{h['loss'][-1]:.4f}", flush=True)
    assert h["loss"][-1] < h["loss"][0]
    stop_orca_context()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.worker:
        worker()
        return

    from zoo_tpu.orca.bootstrap import launch_local_cluster
    # max_restarts=0: SPMD ranks cannot rejoin a formed cluster, so the
    # right policy is group fail-fast (restart budgets suit independent
    # workers, not collective jobs)
    mon = launch_local_cluster(
        args.nproc, os.path.abspath(__file__), ["--worker"],
        local_devices_per_proc=2, max_restarts=0,
        env={"PYTHONPATH": os.pathsep.join(sys.path)})
    mon.wait(timeout=600)
    print("OK")


if __name__ == "__main__":
    main()
