"""Wide & Deep recommendation (reference: ``apps/recommendation-wide-n-deep``
notebook): Friesian-style feature engineering into a ColumnFeatureInfo
layout, then train the WideAndDeep zoo model and rank items per user.

Run: python examples/wide_n_deep_recommendation.py [--epochs 6]
"""

import argparse

import numpy as np
import pandas as pd


def make_interactions(n=6000, users=200, items=100, seed=0):
    """Synthetic interactions with a learnable rule: users like items of
    their own 'genre' (user % 4 == item genre), boosted by recency."""
    rs = np.random.RandomState(seed)
    u = rs.randint(0, users, n)
    i = rs.randint(0, items, n)
    genre = i % 4
    age_bucket = (u % 7).astype(np.int64)
    recency = rs.rand(n).astype(np.float32)
    affinity = (genre == (u % 4)).astype(np.float32)
    p = 0.05 + 0.8 * affinity + 0.1 * recency
    label = (rs.rand(n) < p).astype(np.int64)
    return pd.DataFrame({
        "user": u, "item": i, "genre": genre, "age_bucket": age_bucket,
        "recency": recency, "label": label})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.friesian.feature import FeatureTable
    from zoo_tpu.models.recommendation import (
        ColumnFeatureInfo,
        WideAndDeep,
    )

    init_orca_context(cluster_mode="local")
    df = make_interactions()

    # friesian feature engineering: crossed column + normalized continuous
    tbl = FeatureTable.from_pandas(df)
    tbl = tbl.cross_columns([["user", "genre"]], [512])
    tbl = tbl.min_max_scale(["recency"])
    data = tbl.to_pandas()

    info = ColumnFeatureInfo(
        wide_base_cols=["genre"], wide_base_dims=[4],
        wide_cross_cols=["user_genre"], wide_cross_dims=[512],
        indicator_cols=["age_bucket"], indicator_dims=[7],
        embed_cols=["user", "item"], embed_in_dims=[200, 100],
        embed_out_dims=[16, 16],
        continuous_cols=["recency"])

    x = data[info.feature_cols].to_numpy().astype(np.float32)
    y = data["label"].to_numpy().astype(np.int32)
    cut = int(0.8 * len(x))

    from zoo_tpu.pipeline.api.keras.optimizers import Adam
    model = WideAndDeep(class_num=2, column_info=info,
                        model_type="wide_n_deep")
    model.compile(optimizer=Adam(lr=0.005),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x[:cut], y[:cut], batch_size=128, nb_epoch=args.epochs,
              validation_data=(x[cut:], y[cut:]), verbose=0)
    res = model.evaluate(x[cut:], y[cut:], batch_size=256)
    print("holdout:", res)

    # per-user ranking: affine items (same genre) should outrank others
    probs = np.asarray(model.predict(x[cut:], batch_size=256))[:, 1]
    dfh = data.iloc[cut:].assign(score=probs)
    aff = dfh[dfh.genre == (dfh.user % 4)].score.mean()
    non = dfh[dfh.genre != (dfh.user % 4)].score.mean()
    print(f"mean score affine={aff:.3f} vs other={non:.3f}")
    assert aff > non
    majority = max(y[cut:].mean(), 1 - y[cut:].mean())
    assert res["accuracy"] > majority + 0.02, (res, majority)
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
