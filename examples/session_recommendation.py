"""Session-based recommendation (reference:
``apps/recommendation-session`` style / ``SessionRecommender`` zoo
entry): GRU over the click session + averaged purchase-history tower,
next-item prediction and top-k recommendation.

Run: python examples/session_recommendation.py [--epochs 25]
"""

import argparse

import numpy as np


def make_sessions(n=3000, items=60, sess_len=8, hist_len=4, seed=0):
    """Markov-ish browsing: next item = session tail + user drift."""
    rs = np.random.RandomState(seed)
    sess = rs.randint(1, items + 1, (n, sess_len))
    hist = rs.randint(1, items + 1, (n, hist_len))
    # learnable rule: users re-click the last session item, unless their
    # history starts with an "explorer" item (> items//2) — then the next
    # item is the one after it
    explorer = hist[:, 0] > items // 2
    nxt = np.where(explorer, (sess[:, -1] % items) + 1, sess[:, -1])
    return (sess.astype(np.int32), hist.astype(np.int32),
            nxt.astype(np.int32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=25)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.models.recommendation import SessionRecommender

    init_orca_context(cluster_mode="local")
    items = 60
    sess, hist, nxt = make_sessions(items=items)
    cut = int(0.85 * len(sess))

    model = SessionRecommender(item_count=items, item_embed=32,
                               rnn_hidden_layers=(48, 24),
                               session_length=8, include_history=True,
                               history_length=4)
    from zoo_tpu.pipeline.api.keras.optimizers import Adam
    model.compile(optimizer=Adam(lr=0.003),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit([sess[:cut], hist[:cut]], nxt[:cut], batch_size=128,
              nb_epoch=args.epochs, verbose=0)
    res = model.evaluate([sess[cut:], hist[cut:]], nxt[cut:],
                         batch_size=256)
    print("holdout:", res)

    recs = model.recommend_for_session([sess[cut:cut + 3],
                                        hist[cut:cut + 3]], max_items=3)
    for i, r in enumerate(recs):
        print(f"session {i}: true next={nxt[cut + i]}, top-3={r}")
    assert res["accuracy"] > 0.4, res  # 60-way, chance ~1.7%
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
