"""Image-classification inference (reference:
``pyzoo/zoo/examples/imageclassification/predict.py``): build (or load) a
zoo classifier, run it over an ImageSet with the family's preprocessing
config, optionally int8-quantized (the reference's OpenVINO int8 path →
Pallas int8 MXU matmul here), and print top-k labels.

Run: python examples/image_classification_inference.py \
         [--model squeezenet] [--quantize] [--image-dir DIR]
"""

import argparse
import os

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="squeezenet")
    ap.add_argument("--image-dir", default=None,
                    help="directory of images; synthetic if omitted")
    ap.add_argument("--class-num", type=int, default=10)
    ap.add_argument("--quantize", action="store_true")
    ap.add_argument("--top-k", type=int, default=3)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.feature.image import ImageFeature, ImageSet
    from zoo_tpu.models.image import ImageClassifier
    from zoo_tpu.pipeline.inference.inference_model import quantize_model

    init_orca_context(cluster_mode="local")
    label_map = {i: f"class_{i}" for i in range(args.class_num)}
    clf = ImageClassifier.create(args.model, class_num=args.class_num,
                                 label_map=label_map)
    if args.quantize:
        clf.model.build()
        quantize_model(clf.model)

    if args.image_dir and os.path.isdir(args.image_dir):
        image_set = ImageSet.read(args.image_dir)
    else:
        rs = np.random.RandomState(0)
        image_set = ImageSet([
            ImageFeature(image=(rs.rand(280, 320, 3) * 255)
                         .astype(np.uint8), uri=f"synthetic_{i}.jpg")
            for i in range(6)])

    out = clf.predict_image_set(image_set, top_k=args.top_k)
    for f in out.features:
        pairs = ", ".join(f"{c}:{p:.3f}"
                          for c, p in zip(f["classes"], f["probs"]))
        print(f"{f.get('uri', '?'):22} -> {pairs}")
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
