"""Tiny-Llama causal-LM pre-training (BASELINE.md stretch family):
decoder-only Llama (RMSNorm/RoPE/GQA/SwiGLU) trained next-token on a
synthetic grammar, mixed-bf16 with rematerialized blocks — the exact
recipe that scales to the 8B config under an FSDP×TP mesh
(``docs/parallelism.md``).

Run: python examples/llama_pretrain.py [--epochs 12]
"""

import argparse

import numpy as np


def make_corpus(n=512, seq=24, vocab=96, seed=0):
    """Sequences from a 2-state grammar: even tokens step +2, odd step
    +3 (mod vocab) — enough structure for a tiny LM to compress."""
    rs = np.random.RandomState(seed)
    starts = rs.randint(0, vocab, (n, 1))
    ids = [starts]
    for _ in range(seq):
        prev = ids[-1]
        ids.append(np.where(prev % 2 == 0, prev + 2, prev + 3) % vocab)
    ids = np.concatenate(ids, axis=1)
    return ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.models.llm import Llama, LlamaConfig, llama_param_count
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential

    init_orca_context(cluster_mode="local")
    cfg = LlamaConfig(vocab=96, hidden=96, n_block=3, n_head=6,
                      n_kv_head=2, intermediate=256, rope_theta=10000.0)
    print(f"config: {llama_param_count(cfg) / 1e3:.1f}k params, "
          f"GQA {cfg.n_head}q/{cfg.n_kv_head}kv")

    x, y = make_corpus()
    m = Sequential(name="tiny_llama_pretrain")
    m.add(Llama(cfg, remat=True, input_shape=(x.shape[1],)))
    m.compile(optimizer="adam",
              loss="sparse_categorical_crossentropy_from_logits",
              dtype_policy="mixed_bfloat16")
    h = m.fit(x, y, batch_size=128, nb_epoch=args.epochs, verbose=0)
    print(f"loss {h['loss'][0]:.3f} -> {h['loss'][-1]:.3f} "
          f"(uniform would be {np.log(cfg.vocab):.3f})")
    assert h["loss"][-1] < 1.0, h["loss"]  # grammar is deterministic

    # greedy continuation follows the grammar
    logits = np.asarray(m.predict(x[:4], batch_size=4))
    nxt = logits[:, -1].argmax(-1)
    want = np.where(x[:4, -1] % 2 == 0, x[:4, -1] + 2,
                    x[:4, -1] + 3) % cfg.vocab
    print("greedy next:", nxt, "expected:", want)
    assert (nxt == want).mean() >= 0.75
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
