"""Text classification end-to-end (reference:
``pyzoo/zoo/examples/textclassification/text_classification.py``): TextSet
tokenize → normalize → word2idx → shape_sequence → TextClassifier fit →
predict, all on the TPU-native stack.

Run: python examples/text_classification.py [--encoder cnn] [--epochs 4]
"""

import argparse

import numpy as np


def make_corpus(n_per_class=120, seed=0):
    """Synthetic two-topic corpus (sports vs cooking)."""
    rs = np.random.RandomState(seed)
    sports = ("match score goal team league player win cup final coach "
              "referee stadium crowd defense striker pitch").split()
    cooking = ("recipe oven butter flour sugar bake stir simmer garlic "
               "onion pepper saute whisk dough yeast skillet").split()
    texts, labels = [], []
    for words, label in ((sports, 0), (cooking, 1)):
        for _ in range(n_per_class):
            k = rs.randint(6, 14)
            texts.append(" ".join(rs.choice(words, size=k)))
            labels.append(label)
    order = rs.permutation(len(texts))
    return [texts[i] for i in order], [labels[i] for i in order]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--encoder", default="cnn",
                    choices=["cnn", "lstm", "gru"])
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--sequence-length", type=int, default=20)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.feature.text import TextFeature, TextSet
    from zoo_tpu.models.textclassification import TextClassifier

    init_orca_context(cluster_mode="local")
    texts, labels = make_corpus()
    text_set = TextSet([TextFeature(t, label=l)
                        for t, l in zip(texts, labels)])
    transformed = (text_set.tokenize().normalize()
                   .word2idx(remove_topN=0, max_words_num=2000)
                   .shape_sequence(len=args.sequence_length))
    x, y = transformed.to_arrays()
    vocab = len(transformed.get_word_index()) + 2

    cut = int(0.8 * len(x))
    model = TextClassifier(class_num=2, token_length=64,
                           sequence_length=args.sequence_length,
                           vocab=vocab, encoder=args.encoder)
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x[:cut], y[:cut], batch_size=32, nb_epoch=args.epochs,
              validation_data=(x[cut:], y[cut:]))
    res = model.evaluate(x[cut:], y[cut:], batch_size=32)
    print(f"holdout: {res}")
    preds = model.predict(x[cut:cut + 4], batch_size=4)
    for text, p in zip(texts[cut:cut + 4], np.asarray(preds)):
        print(f"  {text[:40]!r:42} -> class {int(p.argmax())} "
              f"(p={float(p.max()):.2f})")
    assert res.get("accuracy", res.get("acc", 0.0)) > 0.9, res
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
