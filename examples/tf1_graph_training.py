"""TF1 graph-mode training on the TPU fabric (reference: the tfpark
training examples, e.g. ``pyzoo/zoo/examples/tensorflow/tfpark`` — a
user-built TF1 graph with placeholders, variables and a loss tensor,
trained distributed).

The round-5 path: the graph's variables are captured as a JAX params
pytree (``bridges/tf_graph.py``), ``jax.grad`` of the interpreted
forward trains on the mesh, and the trained weights are written back
into the live session so ``tf.train.Saver`` / export flows keep
working. Shown twice: the Orca ``Estimator.from_graph`` surface and the
``TFOptimizer.from_loss`` / ``TFDataset.tensors`` UX.

Run: python examples/tf1_graph_training.py [--epochs 10]
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    args = ap.parse_args()

    import tensorflow as tf
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    init_orca_context(cluster_mode="local")

    rs = np.random.RandomState(0)
    x = rs.randn(512, 10).astype(np.float32)
    w_true = rs.randn(10, 3).astype(np.float32)
    y = np.argmax(x @ w_true + 0.05 * rs.randn(512, 3), 1).astype(np.int32)

    # ---- 1) Estimator.from_graph over a classic TF1 graph -------------
    g = tf1.Graph()
    with g.as_default():
        feat = tf1.placeholder(tf.float32, (None, 10), name="features")
        lbl = tf1.placeholder(tf.int32, (None,), name="labels")
        W1 = tf1.get_variable("W1", shape=(10, 32),
                              initializer=tf1.glorot_uniform_initializer(
                                  seed=0))
        b1 = tf1.get_variable("b1", shape=(32,),
                              initializer=tf1.zeros_initializer())
        hidden = tf.nn.relu(tf.matmul(feat, W1) + b1)
        W2 = tf1.get_variable("W2", shape=(32, 3),
                              initializer=tf1.glorot_uniform_initializer(
                                  seed=1))
        logits = tf.matmul(hidden, W2)
        loss = tf.reduce_mean(
            tf1.nn.sparse_softmax_cross_entropy_with_logits(
                labels=lbl, logits=logits))
        acc = tf.reduce_mean(tf.cast(tf.equal(
            tf.cast(tf.argmax(logits, 1), tf.int32), lbl), tf.float32))

    from zoo.orca.learn.tf.estimator import Estimator
    est = Estimator.from_graph(inputs=[feat], outputs=[logits],
                               labels=[lbl], loss=loss,
                               optimizer="adam", metrics={"acc": acc})
    before = est.evaluate({"x": x, "y": y})
    hist = est.fit({"x": x, "y": y}, epochs=args.epochs, batch_size=64)
    after = est.evaluate({"x": x, "y": y})
    print(f"from_graph: loss {hist['loss'][0]:.4f} -> "
          f"{hist['loss'][-1]:.4f}; acc {before['acc']:.3f} -> "
          f"{after['acc']:.3f}")
    assert after["acc"] > before["acc"]

    # trained weights live in the session: a real Saver checkpoint works
    import tempfile
    ckpt = est.save_tf_checkpoint(
        tempfile.mkdtemp(prefix="tf1_ckpt_") + "/model.ckpt")
    print("tf.train.Saver checkpoint:", ckpt)

    # ---- 2) TFOptimizer.from_loss on TFDataset.tensors -----------------
    from zoo.orca.learn.optimizers import SGD
    from zoo.orca.learn.trigger import MaxEpoch
    from zoo.tfpark import TFDataset, TFOptimizer

    xr = rs.randn(256, 6).astype(np.float32)
    yr = (xr @ rs.randn(6, 1)).astype(np.float32)
    g2 = tf1.Graph()
    with g2.as_default():
        ds = TFDataset.from_ndarrays((xr, yr), batch_size=32)
        f_t, l_t = ds.tensors
        W = tf1.get_variable("W", shape=(6, 1),
                             initializer=tf1.zeros_initializer())
        mse = tf.reduce_mean(tf.square(tf.matmul(f_t, W) - l_t))
        opt = TFOptimizer.from_loss(mse, SGD(lr=0.05))
        h2 = opt.optimize(end_trigger=MaxEpoch(args.epochs))
    print(f"from_loss:  loss {h2['loss'][0]:.5f} -> {h2['loss'][-1]:.5f}")
    assert h2["loss"][-1] < h2["loss"][0] * 0.2

    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
