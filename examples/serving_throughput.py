"""Cluster-serving throughput demo (reference role: the streaming
throughput numbers of ``docs/ClusterServingGuide`` — N concurrent
clients pushing records at the TCP door, the server micro-batching into
the model, per-stage timers reporting where the time went).

Run: python examples/serving_throughput.py \
         [--clients 4] [--records 512] [--batch-size 32]
"""

import argparse
import threading
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--records", type=int, default=512,
                    help="records per client")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--client-batch", type=int, default=32,
                    help="rows per client request")
    args = ap.parse_args()

    from zoo_tpu.models.recommendation import NeuralCF
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.inference.inference_model import InferenceModel
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import TCPInputQueue

    init_orca_context(cluster_mode="local")
    server = None
    try:
        m = NeuralCF(user_count=1000, item_count=2000, class_num=2,
                     user_embed=16, item_embed=16, hidden_layers=(32, 16))
        im = InferenceModel()
        im.load_keras(m)
        server = ServingServer(im, host="127.0.0.1", port=0,
                               batch_size=args.batch_size).start()

        rs = np.random.RandomState(0)
        done = []

        def client(cid):
            iq = TCPInputQueue(host=server.host, port=server.port)
            n = 0
            while n < args.records:
                k = min(args.client_batch, args.records - n)
                x = np.stack([rs.randint(0, 1000, k),
                              rs.randint(0, 2000, k)], 1).astype(np.int32)
                preds = iq.predict(x)
                assert preds.shape[0] == k
                n += k
            iq.close()
            done.append(n)

        # warm the compile outside the timed window
        warm = TCPInputQueue(host=server.host, port=server.port)
        warm.predict(np.zeros((args.client_batch, 2), np.int32))
        warm.close()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        total = sum(done)
        print(f"{args.clients} clients x {args.records} records: "
              f"{total / dt:,.0f} records/s  ({dt * 1e3:.0f}ms total)")
        for stage, timer in server.timers.items():
            s = timer.stats()
            print(f"  stage {stage:9s}: n={s['count']:5.0f} "
                  f"avg={s['avg_ms']:.2f}ms max={s['max_ms']:.2f}ms")
        assert total == args.clients * args.records
        print("OK")
    finally:
        if server is not None:
            server.stop()
        stop_orca_context()


if __name__ == "__main__":
    main()
