"""Object detection end-to-end (reference: ``apps/object-detection`` +
the Scala SSD examples): train a compact SSD on a synthetic two-class
shapes dataset with the multibox loss, run ``predict_detections``, report
detection quality (IoU + label accuracy on held-out images), and write an
annotated image with the predicted boxes drawn.

Run: python examples/object_detection_ssd.py \
         [--epochs 16] [--train-images 96] [--out detections.png]
"""

import argparse

import numpy as np


def make_shapes(n, size=64, seed=0):
    """Bright squares (class 1) and blue bars (class 2) on dim noise;
    one object per image with its normalized gt box."""
    rs = np.random.RandomState(seed)
    imgs = rs.rand(n, size, size, 3).astype(np.float32) * 0.2
    boxes, labels = [], []
    for i in range(n):
        cls = 1 + rs.randint(2)
        if cls == 1:
            w = h = rs.randint(16, 28)
        else:
            w, h = rs.randint(24, 36), rs.randint(8, 14)
        x1 = rs.randint(0, size - w)
        y1 = rs.randint(0, size - h)
        color = (np.array([0.9, 0.8, 0.2]) if cls == 1
                 else np.array([0.2, 0.3, 0.9]))
        imgs[i, y1:y1 + h, x1:x1 + w] = color + 0.05 * rs.randn(h, w, 3)
        boxes.append(np.array([[x1 / size, y1 / size, (x1 + w) / size,
                                (y1 + h) / size]], np.float32))
        labels.append(np.array([cls], np.int32))
    return imgs, boxes, labels


def box_iou(a, b):
    lt = np.maximum(a[:2], b[:2])
    rb = np.minimum(a[2:], b[2:])
    inter = np.prod(np.clip(rb - lt, 0, None))
    return inter / (np.prod(a[2:] - a[:2]) + np.prod(b[2:] - b[:2])
                    - inter + 1e-9)


def draw_detections(img, dets, label_map, path):
    """Annotate and save (cv2 when available, else raw .npy dump)."""
    canvas = (np.clip(img, 0, 1) * 255).astype(np.uint8).copy()
    size = canvas.shape[0]
    try:
        import cv2
    except ImportError:
        np.save(path + ".npy", dets)
        print(f"cv2 unavailable; detection rows saved to {path}.npy")
        return
    for label, score, x1, y1, x2, y2 in dets:
        p1 = (int(x1 * size), int(y1 * size))
        p2 = (int(x2 * size), int(y2 * size))
        cv2.rectangle(canvas, p1, p2, (0, 255, 0), 1)
        name = label_map.get(int(label), str(int(label)))
        cv2.putText(canvas, f"{name}:{score:.2f}",
                    (p1[0], max(p1[1] - 2, 8)), cv2.FONT_HERSHEY_PLAIN,
                    0.7, (0, 255, 0))
    cv2.imwrite(path, cv2.cvtColor(canvas, cv2.COLOR_RGB2BGR))
    print(f"annotated detections written to {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=16)
    ap.add_argument("--train-images", type=int, default=96)
    ap.add_argument("--test-images", type=int, default=8)
    ap.add_argument("--out", default="detections.png")
    args = ap.parse_args()

    from zoo_tpu.models.image import SSD
    from zoo_tpu.orca import init_orca_context, stop_orca_context

    init_orca_context(cluster_mode="local")
    try:
        imgs, boxes, labels = make_shapes(args.train_images)
        model = SSD(n_classes=3, input_size=64,
                    feature_channels=(16, 32))
        hist = model.fit_detection(imgs, boxes, labels,
                                   epochs=args.epochs, batch_size=16,
                                   lr=2e-3, verbose=1)
        print(f"multibox loss {hist[0]:.3f} -> {hist[-1]:.3f}")

        ti, tb, tl = make_shapes(args.test_images, seed=99)
        dets = model.predict_detections(ti, score_threshold=0.3)
        label_map = {1: "square", 2: "bar"}
        hits = 0
        for i, (det, gtb, gtl) in enumerate(zip(dets, tb, tl)):
            ok = (len(det) and box_iou(det[0, 2:], gtb[0]) > 0.4
                  and int(det[0, 0]) == int(gtl[0]))
            hits += bool(ok)
            top = (f"{label_map[int(det[0, 0])]} score={det[0, 1]:.2f}"
                   if len(det) else "none")
            print(f"image {i}: gt={label_map[int(gtl[0])]} "
                  f"top-detection={top} {'OK' if ok else 'MISS'}")
        print(f"held-out detection hits: {hits}/{args.test_images}")
        assert hits >= args.test_images // 2, "detector failed to learn"
        draw_detections(ti[0], dets[0], label_map, args.out)
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
