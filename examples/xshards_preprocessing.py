"""Distributed pandas preprocessing with XShards (reference:
``pyzoo/zoo/examples/orca/data`` — the ``zoo.orca.data.pandas`` ingestion
examples — and the SparkXShards workflow in the Orca user guide): read a
directory of csv files into an XShards of pandas DataFrames, clean and
feature-engineer per shard with plain pandas code, partition by key,
convert to numpy dict shards, and feed an Orca Estimator — the laptop
pandas workflow scaled shard-wise.

Run: python examples/xshards_preprocessing.py [--epochs 4]
"""

import argparse
import os
import tempfile

import numpy as np
import pandas as pd


def write_csv_parts(root, n_parts=4, rows_per_part=600, seed=0):
    """A partitioned 'transactions' table with messy columns to clean."""
    rs = np.random.RandomState(seed)
    os.makedirs(root, exist_ok=True)
    for p in range(n_parts):
        n = rows_per_part
        amount = rs.lognormal(3.0, 1.0, n).round(2)
        hour = rs.randint(0, 24, n)
        region = rs.choice(["north", "south", "east", "west"], n)
        # inject missing values the cleaning stage must handle
        amount[rs.rand(n) < 0.05] = np.nan
        label = ((amount > 40) & (hour >= 18)).astype(np.float32)
        pd.DataFrame({
            "txn_id": np.arange(p * n, (p + 1) * n),
            "amount": amount,
            "hour": hour,
            "region": region,
            "label": label,
        }).to_csv(os.path.join(root, f"part-{p:03d}.csv"), index=False)


def clean_and_featurize(df: pd.DataFrame) -> pd.DataFrame:
    """Runs once per shard — arbitrary pandas, exactly like the reference's
    ``transform_shard`` user functions."""
    df = df.copy()
    df["amount"] = df["amount"].fillna(df["amount"].median())
    df["log_amount"] = np.log1p(df["amount"])
    df["is_evening"] = (df["hour"] >= 18).astype(np.float32)
    region_codes = {"north": 0, "south": 1, "east": 2, "west": 3}
    df["region_code"] = df["region"].map(region_codes).astype(np.float32)
    return df


def to_numpy_shard(df: pd.DataFrame) -> dict:
    feats = ["log_amount", "is_evening", "region_code"]
    return {"x": df[feats].to_numpy(np.float32),
            "y": df[["label"]].to_numpy(np.float32)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=128)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.orca.data.pandas import read_csv
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    init_orca_context(cluster_mode="local")

    root = tempfile.mkdtemp(prefix="zoo_xshards_")
    write_csv_parts(root)

    # one shard per csv part; pandas stays pandas inside the shard
    shards = read_csv(root)
    print(f"read {shards.num_partitions()} shards, "
          f"{sum(len(d) for d in shards.collect())} rows")

    shards = shards.transform_shard(clean_and_featurize)
    # partition_by a key column (the reference's shuffle-by-column role):
    # hash partitioning guarantees equal keys share a shard — a shard can
    # hold several keys, but no key spans two shards
    by_region = shards.partition_by("region_code")
    parts = by_region.collect()
    keys = [sorted(d["region_code"].unique().tolist()) for d in parts]
    print("region keys per partition:", keys)
    assert sum(len(k) for k in keys) == 4  # no key spans two shards

    train = shards.transform_shard(to_numpy_shard)
    model = Sequential()
    model.add(Dense(16, input_shape=(3,), activation="relu"))
    model.add(Dense(1, activation="sigmoid"))
    model.compile(optimizer="adam", loss="binary_crossentropy",
                  metrics=["accuracy"])
    est = Estimator.from_keras(model)
    hist = est.fit(train, epochs=args.epochs, batch_size=args.batch_size)
    res = est.evaluate(train, batch_size=args.batch_size)
    print("loss trajectory:", [round(v, 4) for v in hist["loss"]])
    print("eval:", {k: round(float(v), 4) for k, v in res.items()})

    stop_orca_context()
    assert hist["loss"][-1] < hist["loss"][0], hist["loss"]
    assert res["accuracy"] > 0.8, res
    print("XShards preprocessing example OK")


if __name__ == "__main__":
    main()
