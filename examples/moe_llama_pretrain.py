"""Mixture-of-Experts Llama pre-training with expert parallelism.

Net-new family vs the reference (SURVEY §2.10: EP absent upstream):
a Mixtral-style MoE-Llama (top-2 of E experts per block) trained
next-token on a synthetic grammar, expert banks sharded over the mesh
``expert`` axis so the token dispatch runs as ICI collectives. The
router's load-balance aux loss joins the objective; the script reports
both the task loss trend and the aux term (≈1.0x weight means balanced
routing).

Run: python examples/moe_llama_pretrain.py [--steps 30] [--experts 4]
"""

import argparse

import numpy as np


def make_corpus(n=256, seq=16, vocab=96, seed=0):
    rs = np.random.RandomState(seed)
    starts = rs.randint(0, vocab, (n, 1))
    ids = [starts]
    for _ in range(seq):
        prev = ids[-1]
        ids.append(np.where(prev % 2 == 0, prev + 2, prev + 3) % vocab)
    ids = np.concatenate(ids, axis=1)
    return ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from zoo_tpu.models.llm import (
        LlamaConfig,
        MoELlama,
        place_moe_params,
    )
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.parallel import build_mesh

    init_orca_context(cluster_mode="local")
    try:
        n_dev = len(jax.devices())
        expert_ax = min(args.experts, n_dev) \
            if n_dev % min(args.experts, n_dev) == 0 else 1
        mesh = build_mesh(jax.devices(),
                          axis_sizes={"data": n_dev // expert_ax,
                                      "expert": expert_ax})
        print(f"mesh: data={n_dev // expert_ax} x expert={expert_ax}")

        cfg = LlamaConfig(vocab=96, hidden=64, n_block=2, n_head=4,
                          n_kv_head=2, intermediate=128,
                          rope_theta=10000.0)
        model = MoELlama(cfg, n_experts=args.experts, top_k=2)
        params = place_moe_params(
            model.build(jax.random.PRNGKey(0), (None, 16)), mesh)

        x, y = make_corpus(n=args.batch)
        bsh = NamedSharding(mesh, P("data"))
        xd = jax.device_put(x, bsh)
        yd = jax.device_put(y, bsh)

        def loss_fn(p, b, lbl):
            logits, aux = model.call_with_aux(p, b)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            ce = -jnp.mean(jnp.take_along_axis(logp, lbl[..., None], -1))
            return ce + aux, (ce, aux)

        @jax.jit
        def step(p, b, lbl):
            (_, (ce, aux)), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p, b, lbl)
            p = jax.tree_util.tree_map(lambda w, gr: w - 0.05 * gr, p, g)
            return p, ce, aux

        with mesh:
            first = last = None
            for i in range(args.steps):
                params, ce, aux = step(params, xd, yd)
                if i == 0:
                    first = float(ce)
                last = float(ce)
                if i % 10 == 0:
                    print(f"step {i:3d}: ce={float(ce):.4f} "
                          f"aux={float(aux):.4f}")
        print(f"cross-entropy {first:.3f} -> {last:.3f}")
        assert last < first, "MoE-Llama failed to learn"
        print("OK")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
