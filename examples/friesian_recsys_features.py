"""Friesian feature engineering → NCF training (reference:
``pyzoo/zoo/examples/friesian`` + the Friesian FeatureTable recsys
pipelines): raw interaction logs run through the FeatureTable ops —
string indexing, negative sampling, crossed features — and the
engineered table trains the NCF ranker end-to-end.

Run: python examples/friesian_recsys_features.py [--epochs 12]
"""

import argparse

import numpy as np
import pandas as pd


def make_logs(n_users=60, n_items=120, n_rows=1200, n_clusters=6,
              seed=0):
    """Implicit-feedback logs with classic CF structure: items fall into
    clusters, each user draws 90% of their interactions from their own
    cluster — so a matched (user, item) pair is much likelier to be a
    real interaction than a sampled negative."""
    rs = np.random.RandomState(seed)
    users = rs.randint(0, n_users, n_rows)
    user_cluster = rs.randint(0, n_clusters, n_users)
    per = n_items // n_clusters
    own = (user_cluster[users] * per
           + rs.randint(0, per, n_rows))
    items = np.where(rs.rand(n_rows) < 0.9, own,
                     rs.randint(0, n_items, n_rows))
    return pd.DataFrame({
        "user": [f"u{u}" for u in users],
        "item": items + 1,                     # 1-based ids
        "ts": np.arange(n_rows),
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    args = ap.parse_args()

    from zoo_tpu.friesian.feature import FeatureTable
    from zoo_tpu.models.recommendation import NeuralCF
    from zoo_tpu.orca import init_orca_context, stop_orca_context

    init_orca_context(cluster_mode="local")
    try:
        logs = make_logs()
        tbl = FeatureTable.from_pandas(logs)

        # 1. string-index users (most-frequent-first ids, reference
        #    gen_string_idx semantics)
        [user_idx] = tbl.gen_string_idx("user")
        tbl = tbl.encode_string("user", [user_idx])
        print(f"indexed {user_idx.size} users")

        # 2. negative sampling for implicit feedback (3 negatives per
        #    positive, the reference's add_negative_samples role)
        n_items = int(tbl.df["item"].max())
        tbl = tbl.add_neg_samples(item_size=n_items, item_col="item",
                                  neg_num=3)
        pos = int((tbl.df["label"] == 1).sum())
        neg = int((tbl.df["label"] == 0).sum())
        print(f"after negative sampling: {pos} positives, "
              f"{neg} negatives")

        # 3. train NCF on the engineered table
        df = tbl.df.sample(frac=1.0, random_state=0)
        x = np.stack([df["user"].to_numpy() - 1,
                      df["item"].to_numpy() - 1], axis=1).astype(np.int32)
        y = df["label"].to_numpy().astype(np.int32)
        split = int(0.9 * len(y))
        model = NeuralCF(user_count=user_idx.size, item_count=n_items,
                         class_num=2, user_embed=16, item_embed=16,
                         hidden_layers=(32, 16))
        from zoo_tpu.pipeline.api.keras.optimizers import Adam
        model.compile(optimizer=Adam(lr=0.01),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        model.fit(x[:split], y[:split], batch_size=128,
                  nb_epoch=args.epochs, verbose=0)
        res = model.evaluate(x[split:], y[split:], batch_size=128)
        print(f"held-out: {res}")
        # 25% positives; beating the majority class shows the features
        # carry signal through the pipeline
        assert res["accuracy"] > 0.76, res
        print("OK")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
