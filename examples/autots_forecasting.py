"""AutoTS time-series forecasting (reference: ``apps/automl`` AutoTS
notebooks): TSDataset roll → AutoTSEstimator hyperparameter search over
LSTM/TCN configs → TSPipeline predict/evaluate.

Run: python examples/autots_forecasting.py [--trials 4]
"""

import argparse

import numpy as np
import pandas as pd


def make_series(n=600, seed=0):
    rs = np.random.RandomState(seed)
    t = np.arange(n)
    value = (np.sin(t * 2 * np.pi / 24) + 0.3 * np.sin(t * 2 * np.pi / 168)
             + 0.1 * rs.randn(n))
    return pd.DataFrame({
        "datetime": pd.date_range("2024-01-01", periods=n, freq="h"),
        "value": value.astype(np.float32)})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.automl import hp
    from zoo_tpu.chronos.autots import AutoTSEstimator
    from zoo_tpu.chronos.data import TSDataset

    init_orca_context(cluster_mode="local")
    df = make_series()
    cut = int(len(df) * 0.8)
    train = TSDataset.from_pandas(df.iloc[:cut], dt_col="datetime",
                                  target_col="value")
    val = TSDataset.from_pandas(df.iloc[cut:].reset_index(drop=True),
                                dt_col="datetime", target_col="value")

    est = AutoTSEstimator(
        model="lstm",
        search_space={"hidden_dim": hp.choice([16, 32]),
                      "lr": hp.loguniform(1e-3, 1e-2)},
        past_seq_len=24, future_seq_len=1)
    pipeline = est.fit(train, validation_data=val, epochs=args.epochs,
                       n_sampling=args.trials)
    res = pipeline.evaluate(val, metrics=["mse", "smape"])
    print("best config:", pipeline.best_config)
    print("val:", {k: round(float(v), 4) for k, v in res.items()})
    preds = pipeline.predict(val)
    print("forecast shape:", preds.shape)
    stop_orca_context()
    assert res["mse"] < 0.5
    print("AutoTS example OK")


if __name__ == "__main__":
    main()
