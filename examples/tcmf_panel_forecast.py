"""High-dimensional panel forecasting with TCMF (reference role: the
Chronos TCMF-at-scale story — ``chronos/model/tcmf/DeepGLO.py`` forecasts
thousands of correlated series through a rank-k factorization whose
temporal factors carry a TCN).

Builds a 500-series panel driven by a few nonlinear latent factors,
fits TCMF with both temporal models, and reports the held-out horizon
MSE of each — the TCN should win, that being DeepGLO's point.

Run: python examples/tcmf_panel_forecast.py [--series 500] [--rank 4]
"""

import argparse

import numpy as np


def make_panel(n_series: int, t: int, seed: int = 0):
    """Panel driven by threshold-AR latent factors: nonlinear,
    non-chaotic — exactly predictable given the rule, but outside any
    linear AR's class (a linear factor like a sinusoid would be AR-
    predictable and wash the comparison out)."""
    rs = np.random.RandomState(seed)
    x1 = np.empty(t, np.float32)
    x1[0] = 0.2
    for i in range(1, t):
        x1[i] = 0.95 * x1[i - 1] + (0.4 if x1[i - 1] < 0 else -0.4)
    x2 = np.empty(t, np.float32)
    x2[0] = -0.3
    for i in range(1, t):
        x2[i] = 0.9 * x2[i - 1] + (0.5 if x2[i - 1] < 0.1 else -0.6)
    X = np.stack([x1, x2])
    F = rs.randn(n_series, 2).astype(np.float32)
    return (F @ X + 0.01 * rs.randn(n_series, t)).astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--series", type=int, default=500)
    ap.add_argument("--steps", type=int, default=240)
    ap.add_argument("--horizon", type=int, default=8)
    ap.add_argument("--rank", type=int, default=2)
    ap.add_argument("--tcn-epochs", type=int, default=150)
    args = ap.parse_args()

    from zoo_tpu.chronos.forecaster import TCMFForecaster
    from zoo_tpu.orca import init_orca_context, stop_orca_context

    init_orca_context(cluster_mode="local")
    try:
        Y = make_panel(args.series, args.steps)
        train = Y[:, :-args.horizon]
        test = Y[:, -args.horizon:]
        print(f"panel: {Y.shape[0]} series x {Y.shape[1]} steps, "
              f"forecasting the last {args.horizon}")

        results = {}
        for tm, kw in (("ar", {}),
                       ("tcn", dict(tcn_epochs=args.tcn_epochs,
                                    dropout=0.0, lr=2e-3,
                                    kernel_size=4))):
            f = TCMFForecaster(rank=args.rank, ar_lag=8,
                               temporal_model=tm, **kw)
            fit = f.fit({"y": train})
            pred = f.predict(horizon=args.horizon)
            mse = float(np.mean((pred - test) ** 2))
            results[tm] = mse
            print(f"temporal_model={tm:3s}: reconstruction mse="
                  f"{fit['mse']:.4f}  horizon-{args.horizon} "
                  f"forecast mse={mse:.4f}")
        ratio = results["ar"] / max(results["tcn"], 1e-12)
        print(f"TCN vs AR forecast-MSE ratio: {ratio:.1f}x "
              f"{'(TCN wins)' if ratio > 1 else '(AR wins)'}")
        assert results["tcn"] < results["ar"], results
        print("OK")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
