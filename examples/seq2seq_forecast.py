"""Sequence-to-sequence prediction (reference:
``pyzoo/zoo/examples/seq2seq`` / the Seq2seq model zoo entry): encoder
RNN → RepeatVector bridge → decoder RNN, trained to continue a noisy
multi-channel waveform several steps ahead.

Run: python examples/seq2seq_forecast.py [--epochs 8]
"""

import argparse

import numpy as np


def make_waves(n=768, in_len=20, out_len=5, seed=0):
    rs = np.random.RandomState(seed)
    phase = rs.uniform(0, 2 * np.pi, n)
    freq = rs.uniform(0.15, 0.35, n)
    t = np.arange(in_len + out_len)
    sig = np.sin(phase[:, None] + freq[:, None] * t)[..., None]
    cos = np.cos(phase[:, None] + freq[:, None] * t)[..., None]
    full = np.concatenate([sig, cos], axis=-1).astype(np.float32)
    full[:, :in_len] += 0.02 * rs.randn(n, in_len, 2).astype(np.float32)
    return full[:, :in_len], full[:, in_len:]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    args = ap.parse_args()

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.models.seq2seq import Seq2seq

    init_orca_context(cluster_mode="local")
    x, y = make_waves()
    cut = int(0.8 * len(x))

    model = Seq2seq(input_length=20, input_dim=2, target_length=5,
                    output_dim=2, rnn_type="lstm", hidden_size=64)
    model.compile(optimizer="adam", loss="mse")
    model.fit(x[:cut], y[:cut], batch_size=64, nb_epoch=args.epochs,
              validation_data=(x[cut:], y[cut:]), verbose=0)
    res = model.evaluate(x[cut:], y[cut:], batch_size=128)
    pred = np.asarray(model.predict(x[cut:cut + 1], batch_size=1))
    print("holdout mse:", round(res["loss"], 5))
    print("true   next:", np.round(y[cut, :, 0], 3))
    print("pred   next:", np.round(pred[0, :, 0], 3))
    assert res["loss"] < 0.06, res
    stop_orca_context()
    print("OK")


if __name__ == "__main__":
    main()
