"""Inception-v1 (GoogLeNet) image training (reference:
``pyzoo/zoo/examples/inception/inception.py`` — the ImageNet training
script — and the Scala ``zoo/.../examples/inception`` job): stage an
image dataset as parquet, read it back, train Inception-v1 through the
Orca Keras Estimator, evaluate, and predict a batch.

Synthetic class-colored images stand in for ImageNet so the script always
runs; point ``--data`` at a ``class_name/*.jpg`` directory tree for real
input. Sized down (``--image-size 64``) for the CPU-mesh example matrix;
on a TPU chip use ``--image-size 224`` for the ImageNet geometry.

Run: python examples/inception_training.py [--epochs 3] [--image-size 64]
"""

import argparse
import os
import tempfile

import numpy as np


def make_class_images(n_per_class=24, size=64, seed=0):
    """Two classes separable by channel statistics (red-ish vs blue-ish)."""
    rs = np.random.RandomState(seed)
    arrays, labels = [], []
    for label, tint in ((0, (0.8, 0.2, 0.2)), (1, (0.2, 0.2, 0.8))):
        for _ in range(n_per_class):
            img = rs.rand(size, size, 3) * 0.4 + np.asarray(tint) * 0.6
            arrays.append(img.astype(np.float32))
            labels.append(label)
    order = rs.permutation(len(arrays))
    return (np.stack([arrays[i] for i in order]),
            np.asarray([labels[i] for i in order], np.int32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--data", default=None,
                    help="optional class_name/*.jpg directory tree")
    args = ap.parse_args()

    from zoo_tpu.models.image import inception_v1
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.orca.data.parquet_dataset import (
        ParquetDataset,
        write_ndarrays,
    )
    from zoo_tpu.orca.learn.keras import Estimator

    init_orca_context(cluster_mode="local")
    size = args.image_size

    # --- stage the dataset as parquet (the reference stages ImageNet as
    # Hadoop sequence files; parquet is the rebuild's columnar format) ---
    staging = tempfile.mkdtemp(prefix="zoo_inception_")
    if args.data and os.path.isdir(args.data):
        from zoo_tpu.feature.image import ImageSet
        iset = ImageSet.read(args.data, with_label=True,
                             resize_height=size, resize_width=size)
        x = np.stack([np.asarray(f["image"], np.float32) / 255.0
                      for f in iset.features])
        y = np.asarray([f["label"] for f in iset.features], np.int32)
    else:
        x, y = make_class_images(n_per_class=24, size=size)
    write_ndarrays(x, y, os.path.join(staging, "train"), block_size=16)
    data = ParquetDataset.read_as_arrays(os.path.join(staging, "train"))
    n_class = int(data["label"].max()) + 1

    model = inception_v1(class_num=n_class, input_shape=(size, size, 3))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    est = Estimator.from_keras(model)
    hist = est.fit({"x": data["image"], "y": data["label"]},
                   epochs=args.epochs, batch_size=args.batch_size)
    print("loss trajectory:", [round(v, 4) for v in hist["loss"]])

    res = est.evaluate({"x": data["image"], "y": data["label"]},
                       batch_size=args.batch_size)
    print("eval:", {k: round(float(v), 4) for k, v in res.items()})

    preds = np.asarray(est.predict(data["image"][:8],
                                   batch_size=args.batch_size))
    print("sample predictions:", preds.argmax(-1).tolist())

    stop_orca_context()
    assert hist["loss"][-1] < hist["loss"][0], hist["loss"]
    print("Inception training example OK")


if __name__ == "__main__":
    main()
